"""Self-healing elastic fleet: process supervisor + autoscaler daemon.

Two classes, one line between them:

- :class:`ReplicaManager` is *transport*: it spawns real
  :class:`~sparkflow_tpu.serving.server.InferenceServer` processes (via a
  caller-supplied ``launcher``), waits for ``/healthz``, registers them
  with :class:`~sparkflow_tpu.serving.membership.Membership`, SIGTERM-
  drains them on scale-down (the PR 7 drain machinery finishes in-flight
  work behind a 503 ``/healthz``), hard-kills the ones that will not die,
  and reaps exit codes so a crash is noticed within one tick rather than
  after ``failure_threshold`` probe misses.
- :class:`Autoscaler` is the *control loop*: each tick it reaps crashes,
  snapshots the fleet (``Membership.views()`` — router-side in-flight,
  probe-reported ``decode/{free_slots,pages_free}``), reads the queue-wait
  p95 from the router's ``router/request_ms`` histogram, and feeds all of
  it to the pure :func:`~sparkflow_tpu.serving.policies.scale_decision` —
  the SAME function the fleet simulator replays, so bands and cooldowns
  tuned in ``sparkflow_tpu.sim`` transfer to production unchanged. The
  daemon only *applies* the returned action.

Failure discipline:

- ``spawn`` fires the ``autoscaler.spawn`` fault point and is bounded by
  a :class:`~sparkflow_tpu.resilience.retry.RetryPolicy` — a replica that
  dies before becoming healthy is killed and retried with backoff, and
  :class:`~sparkflow_tpu.resilience.retry.RetryExhausted` surfaces to the
  tick loop, which logs, counts, and tries again next tick (the policy's
  below-min rule keeps asking until the fleet recovers).
- ``drain`` fires ``autoscaler.drain``; a replica that ignores SIGTERM
  past ``drain_timeout_s`` is SIGKILLed — scale-down must converge.
- Crash replacement deregisters the dead record (its gauges go with it)
  and spawns a fresh process; the replacement gets a never-recycled index.

The tick publishes ``autoscaler/{replicas,target,spawns,drains,
replacements,last_decision}`` gauges so the exposition shows what the
controller last did and why-shaped counters accumulate across the run.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.flight import harvest_flight
from ..resilience import faults
from ..resilience.retry import RetryExhausted, RetryPolicy
from ..utils import metrics as metrics_mod
from . import policies
from .client import ServingClient
from .membership import BreakerState, Membership, Replica

__all__ = ["Autoscaler", "ReplicaManager", "free_port"]

logger = logging.getLogger("sparkflow_tpu")

# numeric codes for the autoscaler/last_decision gauge (Prometheus gauges
# are floats; the mapping is part of the exposition contract)
DECISION_CODES = {policies.SCALE_HOLD: 0.0, policies.SCALE_UP: 1.0,
                  policies.SCALE_DOWN: 2.0, policies.SCALE_REPLACE: 3.0}


def free_port() -> int:
    """An OS-assigned free TCP port (racy by nature; spawn retries absorb
    the rare collision)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Managed:
    """One supervised replica process: the Popen-like handle (``poll`` /
    ``terminate`` / ``kill`` / ``wait``), its URL, and the Membership
    record it registered as."""

    __slots__ = ("proc", "url", "replica")

    def __init__(self, proc, url: str, replica: Replica):
        self.proc = proc
        self.url = url
        self.replica = replica


class ReplicaManager:
    """Spawns, drains, kills, and reaps replica server processes.

    Parameters
    ----------
    launcher : Callable[[int], process]
        Starts a replica server on the given port and returns a
        Popen-like handle (``poll()``, ``terminate()``, ``kill()``,
        ``wait(timeout)``). Tests pass fakes; examples re-invoke
        themselves with ``--replica PORT``.
    membership : Membership
        Fleet table new replicas register with (and leave on drain).
    retry : RetryPolicy, optional
        Bounds spawn attempts (default: 3 attempts, 0.2 s base backoff).
    health_timeout_s : float
        How long one spawn attempt waits for a green ``/healthz`` before
        the process is killed and the attempt counts as failed.
    drain_timeout_s : float
        SIGTERM-to-SIGKILL grace on scale-down.
    flight_dir : str, optional
        Directory where managed replicas write their flight-recorder
        files (``replica-<port>.jsonl``). When set, :meth:`drain` and
        :meth:`destroy` harvest the dead replica's record — in-flight
        trace ids and the last dumped spans — into
        :attr:`flight_reports` before the record is dropped.
    """

    def __init__(self, launcher: Callable[[int], object], *,
                 membership: Membership,
                 retry: Optional[RetryPolicy] = None,
                 port_factory: Callable[[], int] = free_port,
                 health_timeout_s: float = 60.0,
                 drain_timeout_s: float = 10.0,
                 poll_interval_s: float = 0.2,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 flight_dir: Optional[str] = None):
        self.launcher = launcher
        self.membership = membership
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_s=0.2, max_s=2.0)
        self.port_factory = port_factory
        self.health_timeout_s = float(health_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.metrics = (metrics if metrics is not None
                        else membership.metrics)
        self.flight_dir = flight_dir
        self._lock = threading.Lock()
        self._managed: Dict[int, _Managed] = {}  # replica.index -> record
        # harvested flight records of dead replicas, newest last (bounded)
        self.flight_reports: List[Dict[str, Any]] = []

    # -- introspection -------------------------------------------------------

    def owns(self, replica: Replica) -> bool:
        with self._lock:
            return replica.index in self._managed

    @property
    def managed_count(self) -> int:
        with self._lock:
            return len(self._managed)

    def managed(self) -> List[Replica]:
        with self._lock:
            return [m.replica for m in self._managed.values()]

    # -- spawn ---------------------------------------------------------------

    def _wait_healthy(self, url: str, proc) -> None:
        client = ServingClient(url, retries=0)
        try:
            deadline = time.monotonic() + self.health_timeout_s
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica at {url} exited with code "
                        f"{proc.poll()} before becoming healthy")
                try:
                    if client.healthz(timeout_s=1.0).get("status") == "ok":
                        return
                except Exception:  # noqa: BLE001 - not up yet
                    pass
                time.sleep(self.poll_interval_s)
            raise TimeoutError(f"replica at {url} not healthy within "
                               f"{self.health_timeout_s:.0f}s")
        finally:
            client.close()

    def _spawn_attempt(self) -> Tuple[object, str]:
        # the fault point sits INSIDE the attempt so an injected failure
        # exercises the retry path, not just the caller's error handling
        faults.fire("autoscaler.spawn")
        port = self.port_factory()
        url = f"http://127.0.0.1:{port}"
        proc = self.launcher(port)
        try:
            self._wait_healthy(url, proc)
        except Exception:
            # a half-started process must not leak past a failed attempt
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - already gone
                pass
            raise
        return proc, url

    def spawn(self) -> Replica:
        """Start one replica, wait for health, register it. Retries are
        bounded by the manager's ``RetryPolicy``; exhaustion raises
        :class:`RetryExhausted` to the caller (the autoscaler tick)."""
        proc, url = self.retry.call(self._spawn_attempt,
                                    describe="autoscaler.spawn")
        replica = self.membership.register(url)
        with self._lock:
            self._managed[replica.index] = _Managed(proc, url, replica)
        self.metrics.incr("autoscaler/spawn_total")
        logger.info("autoscaler: spawned replica %s (index %d)",
                    url, replica.index)
        return replica

    def adopt(self, replica: Replica, proc, url: Optional[str] = None
              ) -> None:
        """Take over supervision of an already-running replica process —
        the founding fleet a RouterServer was created with, so crash
        replacement and drain cover it too."""
        with self._lock:
            self._managed[replica.index] = _Managed(
                proc, url if url is not None else replica.url, replica)

    # -- drain / kill / reap -------------------------------------------------

    def _pop(self, replica: Replica) -> Optional[_Managed]:
        with self._lock:
            return self._managed.pop(replica.index, None)

    def _harvest(self, replica: Replica, reason: str) -> None:
        """Read the dead replica's flight-recorder file (after the process
        is gone, so the file is settled) and keep the report: which trace
        ids were in flight when it died, plus the last dumped spans if the
        death was graceful enough to dump (SIGTERM yes, SIGKILL no)."""
        path = replica.flight_path
        if path is None and self.flight_dir is not None:
            path = os.path.join(self.flight_dir,
                                f"replica-{replica.port}.jsonl")
        if path is None:
            return
        try:
            report = harvest_flight(path)
        except Exception:  # noqa: BLE001 - torn file must not block reaping
            logger.exception("autoscaler: flight harvest failed for %s",
                             replica.url)
            return
        if report is None:
            return
        report["replica_url"] = replica.url
        # "reason" (if present) is the replica's own dump reason, e.g.
        # "signal:15"; this is why the MANAGER removed it
        report["harvest_reason"] = reason
        with self._lock:
            self.flight_reports.append(report)
            del self.flight_reports[:-64]
        self.metrics.incr("autoscaler/flight_harvested")
        inflight = report.get("inflight_trace_ids", [])
        if inflight:
            logger.warning(
                "autoscaler: replica %s died (%s) with %d in-flight "
                "trace(s): %s", replica.url, reason, len(inflight),
                ", ".join(inflight[:8]))

    def drain(self, replica: Replica, reason: str = "scale-down") -> None:
        """Graceful scale-down: eject from rotation now, SIGTERM (the
        server's lifecycle finishes in-flight work), wait, SIGKILL past
        the grace, deregister (gauges drop with the record)."""
        faults.fire("autoscaler.drain")
        m = self._pop(replica)
        self.membership.eject(replica, reason)
        if m is not None:
            try:
                m.proc.terminate()
                m.proc.wait(timeout=self.drain_timeout_s)
            except Exception:  # noqa: BLE001 - stuck past the grace
                logger.warning("autoscaler: replica %s ignored SIGTERM; "
                               "killing", replica.url)
                try:
                    m.proc.kill()
                    m.proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001 - already gone
                    pass
        self._harvest(replica, reason)
        self.membership.deregister(replica)
        logger.info("autoscaler: drained replica %s (%s)",
                    replica.url, reason)

    def destroy(self, replica: Replica, reason: str = "crash") -> None:
        """Hard removal (crash replacement): kill whatever is left of the
        process and drop the record — no drain, the work is already lost."""
        m = self._pop(replica)
        if m is not None and m.proc.poll() is None:
            try:
                m.proc.kill()
                m.proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - already gone
                pass
        self._harvest(replica, reason)
        self.membership.deregister(replica)
        logger.warning("autoscaler: destroyed replica %s (%s)",
                       replica.url, reason)

    def reap(self) -> List[Tuple[Replica, int]]:
        """Exit-code sweep: every managed process that has terminated,
        as ``(replica, returncode)``. The records stay managed — the
        autoscaler decides whether the death is a crash to replace or a
        drain that already completed elsewhere."""
        dead = []
        with self._lock:
            for m in self._managed.values():
                rc = m.proc.poll()
                if rc is not None:
                    dead.append((m.replica, rc))
        return dead

    def stop_all(self, *, kill: bool = False) -> None:
        """Tear down every managed replica (test/example cleanup)."""
        for replica in self.managed():
            if kill:
                self.destroy(replica, reason="shutdown")
            else:
                self.drain(replica, reason="shutdown")


class Autoscaler:
    """Daemon that closes the loop between fleet telemetry and the pure
    scaling policy.

    Each :meth:`tick`:

    1. reaps crashed processes (``ReplicaManager.reap``) and trips their
       breakers/health so the router stops picking them immediately;
    2. snapshots the fleet (``Membership.views()``), marking reaped and
       breaker-open replicas unhealthy — the policy sees crashes the
       prober has not noticed yet;
    3. reads the queue-wait p95 signal (default: the router's
       ``router/request_ms`` histogram; injectable for tests);
    4. calls :func:`policies.scale_decision` with the carried
       :class:`policies.AutoscalerState`;
    5. applies the action — spawn / drain / destroy+respawn — and
       publishes the ``autoscaler/*`` gauges.

    ``start()`` runs ticks on a daemon thread every ``interval_s``;
    ``tick()`` is public so tests and examples can step the loop
    deterministically.
    """

    def __init__(self, membership: Membership, manager: ReplicaManager, *,
                 targets: Optional[policies.ScaleTargets] = None,
                 interval_s: float = 1.0,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 queue_wait_signal: Optional[
                     Callable[[], Optional[float]]] = None,
                 signal_name: str = "router/request_ms",
                 signal_window: int = 256):
        self.membership = membership
        self.manager = manager
        self.targets = targets if targets is not None \
            else policies.ScaleTargets()
        self.interval_s = float(interval_s)
        self.metrics = (metrics if metrics is not None
                        else membership.metrics)
        self._clock = clock
        self.signal_name = signal_name
        self.signal_window = int(signal_window)
        self._signal = queue_wait_signal
        self.state = policies.AutoscalerState(
            desired=max(self.targets.min_replicas,
                        len(membership.replicas)))
        self.spawns = 0
        self.drains = 0
        self.replacements = 0
        self.spawn_failures = 0
        self.last_action: Optional[policies.ScaleAction] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal --------------------------------------------------------------

    def queue_wait_p95_ms(self) -> Optional[float]:
        """The scaling signal: p95 of the router's end-to-end request
        latency histogram (queue wait dominates it under saturation),
        windowed to the last ``signal_window`` samples so a long-past
        overload burst doesn't pin the signal high forever. None while
        the histogram is empty (idle fleet)."""
        if self._signal is not None:
            return self._signal()
        try:
            return self.metrics.percentile(self.signal_name, 95,
                                           window=self.signal_window)
        except (KeyError, ValueError):
            return None

    # -- one tick ------------------------------------------------------------

    def tick(self) -> policies.ScaleAction:
        now = self._clock()

        # 1. exit-code reaping: a crash is actionable this tick, not
        #    failure_threshold probe intervals from now
        reaped: Dict[int, Replica] = {}
        for replica, rc in self.manager.reap():
            reaped[replica.index] = replica
            self.membership.eject(replica, f"exit code {rc}")
            logger.warning("autoscaler: replica %s exited with code %d",
                           replica.url, rc)

        # 2. fleet snapshot; reaped + breaker-open managed replicas are
        #    dead to the policy even if their last probe was green (the
        #    overlay clears the probe-miss debounce: an exit code or a
        #    tripped breaker is definitive, a single missed probe is not),
        #    and unmanaged (founding-fleet) records are flagged so the
        #    policy never orders a kill there is no process handle for
        managed = self.manager.managed()
        managed_idx = {r.index for r in managed}
        tripped = {r.index for r in managed
                   if r.breaker.state is BreakerState.OPEN}
        views = []
        for v in self.membership.views(now):
            if v.index in reaped or v.index in tripped:
                v = dataclasses.replace(
                    v, healthy=False,
                    probe_misses=max(v.probe_misses,
                                     self.targets.dead_after_misses))
            if v.index not in managed_idx:
                v = dataclasses.replace(v, managed=False)
            views.append(v)

        # 3-4. the pure decision
        action = policies.scale_decision(
            views, self.targets, self.state, now,
            queue_wait_p95_ms=self.queue_wait_p95_ms())

        # 5. apply
        by_index = {r.index: r for r in self.membership.replicas}
        if action.kind == policies.SCALE_REPLACE:
            for idx in action.targets:
                replica = reaped.get(idx) or by_index.get(idx)
                # the policy only targets managed views; the owns() check
                # guards the race where a drain landed between snapshot
                # and apply. Unmanaged records are never destroyed or
                # deregistered here — a recovered probe re-admits them,
                # and the below-min rule refills capacity around them.
                if replica is None or not self.manager.owns(replica):
                    continue
                self.manager.destroy(replica)
                try:
                    self.manager.spawn()
                    self.replacements += 1
                    self.spawns += 1
                except RetryExhausted as exc:
                    # next tick sees the fleet below min and retries
                    self.spawn_failures += 1
                    logger.error("autoscaler: replacement spawn failed "
                                 "(%s); will retry next tick", exc)
        elif action.kind == policies.SCALE_UP:
            for _ in range(action.count):
                try:
                    self.manager.spawn()
                    self.spawns += 1
                except RetryExhausted as exc:
                    self.spawn_failures += 1
                    logger.error("autoscaler: scale-up spawn failed (%s); "
                                 "will retry next tick", exc)
                    break
        elif action.kind == policies.SCALE_DOWN:
            applied = 0
            for idx in action.targets:
                replica = by_index.get(idx)
                if replica is None or not self.manager.owns(replica):
                    logger.info("autoscaler: skipping scale-down of "
                                "unmanaged or departed replica %d", idx)
                    continue
                self.manager.drain(replica)
                self.drains += 1
                applied += 1
            if applied == 0:
                # nothing actually drained: committing the successor state
                # would drift the target gauge below the real fleet size
                # and burn the down-cooldown on a no-op
                action = dataclasses.replace(action, state=self.state)

        self.state = action.state
        self.last_action = action
        self.publish_gauges()
        return action

    def publish_gauges(self) -> None:
        m = self.metrics
        m.gauge("autoscaler/replicas", float(len(self.membership.replicas)))
        m.gauge("autoscaler/target", float(self.state.desired))
        m.gauge("autoscaler/spawns", float(self.spawns))
        m.gauge("autoscaler/drains", float(self.drains))
        m.gauge("autoscaler/replacements", float(self.replacements))
        m.gauge("autoscaler/last_decision",
                DECISION_CODES.get(
                    self.last_action.kind if self.last_action else
                    policies.SCALE_HOLD, 0.0))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive a tick
                logger.exception("autoscaler: tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # a stopped autoscaler must not keep stale replicas/target in a
        # shared registry — the next controller would read its ghost
        self.metrics.remove_prefix("autoscaler/")
