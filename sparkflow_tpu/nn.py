"""Model-definition DSL used inside ``build_graph`` model functions.

This replaces the raw TF1 ops the reference's users write inside ``build_graph``
model functions (``tf.placeholder`` / ``tf.layers.dense`` / ``tf.losses.*`` — see
reference ``examples/simple_dnn.py:13-22``). The API is deliberately shaped like
TF1's so a sparkflow model function ports line-for-line:

    import sparkflow_tpu.nn as nn

    def small_model():
        x = nn.placeholder([None, 784], name='x')
        y = nn.placeholder([None, 10], name='y')
        h = nn.dense(x, 256, activation='relu')
        h = nn.dense(h, 256, activation='relu')
        out = nn.dense(h, 10)
        z = nn.argmax(out, 1, name='out')
        loss = nn.softmax_cross_entropy(y, out)
        return loss

Under the hood each call appends a node to the active :class:`~sparkflow_tpu.graphdef.GraphDef`
(a JSON-serializable dataflow spec executed by JAX), instead of mutating a global
TF graph. Loss functions auto-register in the graph's loss collection, mirroring
``tf.losses.*`` adding to ``tf.GraphKeys.LOSSES`` (consumed by the reference at
``sparkflow/HogwildSparkModel.py:50``).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence, Union

import numpy as np

from .graphdef import GraphDef, _TF_ACT_SCOPE

_state = threading.local()


class Sym:
    """Symbolic tensor: a handle to a node in the graph being built."""

    __slots__ = ("graph", "node_id")

    def __init__(self, graph: GraphDef, node_id: int):
        self.graph = graph
        self.node_id = node_id

    @property
    def name(self) -> str:
        return f"{self.graph.nodes[self.node_id].name}:0"

    @property
    def shape(self):
        # lazily infer via a throwaway GraphModel would be heavy; shapes are
        # re-derived at execution. Expose the declared placeholder shape only.
        node = self.graph.nodes[self.node_id]
        return node.attrs.get("shape")

    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(other, self)

    def __sub__(self, other):
        return subtract(self, other)

    def __rsub__(self, other):
        return subtract(other, self)

    def __mul__(self, other):
        return multiply(self, other)

    def __rmul__(self, other):
        return multiply(other, self)

    def __repr__(self):
        return f"Sym({self.name})"


def current_graph() -> GraphDef:
    g = getattr(_state, "graph", None)
    if g is None:
        raise RuntimeError(
            "no active graph: model-definition ops must run inside "
            "sparkflow_tpu.graph_utils.build_graph(model_fn)")
    return g


class graph_scope:
    """Context manager installing a fresh GraphDef as the active graph."""

    def __init__(self, graph: Optional[GraphDef] = None):
        self.graph = graph or GraphDef()

    def __enter__(self) -> GraphDef:
        self._prev = getattr(_state, "graph", None)
        _state.graph = self.graph
        return self.graph

    def __exit__(self, *exc):
        _state.graph = self._prev
        return False


def _ids(vals: Sequence[Union[Sym, float, int]]):
    """Resolve op inputs to node ids, lifting Python scalars to constants."""
    g = current_graph()
    out = []
    for v in vals:
        if isinstance(v, Sym):
            out.append(v.node_id)
        else:
            node = g.add_node("constant", "const", [], {"value": v})
            out.append(node.id)
    return out


def _op(op: str, inputs: Sequence[Any], attrs: dict, name: Optional[str] = None) -> Sym:
    g = current_graph()
    node = g.add_node(op, name, _ids(inputs), attrs)
    return Sym(g, node.id)


# -- inputs ------------------------------------------------------------------

def placeholder(*args, name: Optional[str] = None, dtype: str = "float32",
                shape=None) -> Sym:
    """Declare a model input. ``shape=[None, d]`` — None marks the batch dim.

    Positional forms accepted (TF1 model functions are written both ways):
    ``placeholder([None, d], 'x')`` (shape-first, this framework's native form)
    and ``placeholder('float', [None, d], 'x')`` / ``placeholder('float',
    shape=[...], name=...)`` (tf.placeholder's dtype-first ordering, reference
    ``examples/autoencoder_example.py:11``).
    """
    args = list(args)
    pos: dict = {}
    if args and isinstance(args[0], str):  # TF1 ordering: (dtype, shape, name)
        order = ["dtype", "shape", "name"]
    else:  # native ordering: (shape, name, dtype)
        order = ["shape", "name", "dtype"]
    if len(args) > len(order):
        raise TypeError(f"placeholder takes at most {len(order)} positional "
                        f"arguments ({len(args)} given)")
    for slot, val in zip(order, args):
        pos[slot] = val
    for slot, kw in (("shape", shape), ("name", name), ("dtype", dtype)):
        if slot in pos and kw is not None and slot != "dtype" :
            raise TypeError(f"placeholder got multiple values for {slot!r}")
    shape = pos.get("shape", shape)
    name = pos.get("name", name)
    dtype = pos.get("dtype", dtype)
    if shape is None:
        raise ValueError("placeholder requires a shape")
    if dtype in ("float", "float32", "f32"):
        dtype = "float32"
    shape = [None if d is None else int(d) for d in shape]
    return _op("placeholder", [], {"shape": shape, "dtype": dtype}, name or "placeholder")


def placeholder_with_default(default, shape=None, name: Optional[str] = None,
                             dtype: str = "float32") -> Sym:
    """A placeholder that evaluates to ``default`` when not fed — the TF1
    ``tf.placeholder_with_default`` pattern users need for dropout keep-prob
    (fed 1.0/0.0 at predict time via the estimator's ``tfDropout`` param,
    reference ``sparkflow/ml_util.py:70-71``; unfed during training)."""
    if shape is None:
        shape = list(np.asarray(default).shape) if hasattr(default, "shape") else []
    return _op("placeholder", [],
               {"shape": list(shape), "dtype": dtype, "default": default},
               name or "placeholder")


def constant(value, name: Optional[str] = None, dtype: str = "float32") -> Sym:
    return _op("constant", [], {"value": value, "dtype": dtype}, name or "const")


# -- layers ------------------------------------------------------------------

def dense(x: Sym, units: int, activation: Optional[str] = None,
          name: Optional[str] = None, use_bias: bool = True,
          kernel_initializer: str = "glorot_uniform",
          bias_initializer: str = "zeros") -> Sym:
    """Fully-connected layer (``tf.layers.dense`` analog).

    With ``activation='sigmoid'`` and ``name='out'``, the post-activation tensor
    is addressable as ``'out/Sigmoid:0'`` (TF1 scope-naming compat) as well as
    ``'out:0'``.
    """
    g = current_graph()
    base = g.unique_name(name or "dense")
    node = g.add_node("dense", f"{base}/BiasAdd" if use_bias else f"{base}/MatMul",
                      _ids([x]),
                      {"units": int(units), "use_bias": use_bias,
                       "kernel_init": kernel_initializer, "bias_init": bias_initializer})
    out = Sym(g, node.id)
    if activation is not None:
        act_name = f"{base}/{_TF_ACT_SCOPE.get(activation, activation)}"
        out = _op(activation, [out], {}, act_name)
    g.add_alias(f"{base}:0", out.node_id)
    return out


def conv2d(x: Sym, filters: int, kernel_size, strides=1, padding: str = "valid",
           activation: Optional[str] = None, name: Optional[str] = None,
           use_bias: bool = True, kernel_initializer: str = "glorot_uniform") -> Sym:
    """2-D convolution over NHWC input (``tf.layers.conv2d`` analog)."""
    g = current_graph()
    base = g.unique_name(name or "conv2d")
    node = g.add_node("conv2d", f"{base}/BiasAdd", _ids([x]),
                      {"filters": int(filters), "kernel_size": kernel_size,
                       "strides": strides, "padding": padding.upper(),
                       "use_bias": use_bias, "kernel_init": kernel_initializer})
    out = Sym(g, node.id)
    if activation is not None:
        out = _op(activation, [out], {}, f"{base}/{_TF_ACT_SCOPE.get(activation, activation)}")
    g.add_alias(f"{base}:0", out.node_id)
    return out


def max_pooling2d(x: Sym, pool_size, strides=None, padding: str = "valid",
                  name: Optional[str] = None) -> Sym:
    return _op("max_pool2d", [x],
               {"pool_size": pool_size, "strides": strides or pool_size,
                "padding": padding.upper()}, name or "max_pool")


def avg_pooling2d(x: Sym, pool_size, strides=None, padding: str = "valid",
                  name: Optional[str] = None) -> Sym:
    return _op("avg_pool2d", [x],
               {"pool_size": pool_size, "strides": strides or pool_size,
                "padding": padding.upper()}, name or "avg_pool")


def flatten(x: Sym, name: Optional[str] = None) -> Sym:
    return _op("flatten", [x], {}, name or "flatten")


def reshape(x: Sym, shape, name: Optional[str] = None) -> Sym:
    return _op("reshape", [x], {"shape": [int(d) for d in shape]}, name or "reshape")


def dropout(x: Sym, keep_prob: Union[Sym, float, None] = None,
            rate: Union[Sym, float, None] = None, name: Optional[str] = None) -> Sym:
    """Dropout. ``keep_prob`` follows TF1 ``tf.nn.dropout`` semantics (fraction
    to KEEP); ``rate`` follows TF2/torch semantics (fraction to DROP). Either may
    be a placeholder ``Sym`` so inference can feed 1.0/0.0 — this is what the
    estimator's ``tfDropout``/``toKeepDropout`` params drive (reference
    ``sparkflow/ml_util.py:70-71``)."""
    if (keep_prob is None) == (rate is None):
        raise ValueError("pass exactly one of keep_prob / rate")
    mode = "keep" if keep_prob is not None else "drop"
    p = keep_prob if keep_prob is not None else rate
    if isinstance(p, Sym):
        return _op("dropout", [x, p], {"mode": mode}, name or "dropout")
    return _op("dropout", [x], {"mode": mode, "rate": float(p)}, name or "dropout")


def layer_norm(x: Sym, epsilon: float = 1e-6, name: Optional[str] = None) -> Sym:
    return _op("layer_norm", [x], {"epsilon": epsilon}, name or "layer_norm")


def batch_norm(x: Sym, epsilon: float = 1e-5, name: Optional[str] = None) -> Sym:
    """Batch-statistics normalization (stateless — see graphdef._eval_batch_norm
    for the train/serve caveat vs TF1's moving averages)."""
    return _op("batch_norm", [x], {"epsilon": epsilon}, name or "batch_norm")


def embedding(ids: Sym, vocab_size: int, dim: int, name: Optional[str] = None) -> Sym:
    return _op("embedding", [ids], {"vocab_size": int(vocab_size), "dim": int(dim)},
               name or "embedding")


# -- pointwise / math --------------------------------------------------------

def _unary(op_name):
    def fn(x: Sym, name: Optional[str] = None) -> Sym:
        return _op(op_name, [x], {}, name or op_name)
    fn.__name__ = op_name
    return fn


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
softmax = _unary("softmax")
log_softmax = _unary("log_softmax")
gelu = _unary("gelu")
elu = _unary("elu")
leaky_relu = _unary("leaky_relu")
softplus = _unary("softplus")
swish = _unary("swish")


def argmax(x: Sym, axis: int = 1, name: Optional[str] = None) -> Sym:
    return _op("argmax", [x], {"axis": int(axis)}, name or "argmax")


def add(a, b, name: Optional[str] = None) -> Sym:
    return _op("add", [a, b], {}, name or "add")


def subtract(a, b, name: Optional[str] = None) -> Sym:
    return _op("subtract", [a, b], {}, name or "subtract")


def multiply(a, b, name: Optional[str] = None) -> Sym:
    return _op("multiply", [a, b], {}, name or "multiply")


def matmul(a: Sym, b: Sym, name: Optional[str] = None) -> Sym:
    return _op("matmul", [a, b], {}, name or "matmul")


def concat(xs: Sequence[Sym], axis: int = -1, name: Optional[str] = None) -> Sym:
    return _op("concat", list(xs), {"axis": int(axis)}, name or "concat")


# -- losses (auto-register, like tf.losses.*) --------------------------------

def _loss(op_name):
    def fn(labels: Sym, predictions: Sym, name: Optional[str] = None, **attrs) -> Sym:
        s = _op(op_name, [labels, predictions], attrs, name or op_name)
        s.graph.register_loss(s.node_id)
        return s
    fn.__name__ = op_name
    return fn


softmax_cross_entropy = _loss("softmax_cross_entropy")
sigmoid_cross_entropy = _loss("sigmoid_cross_entropy")
mean_squared_error = _loss("mean_squared_error")
absolute_difference = _loss("absolute_difference")
huber_loss = _loss("huber_loss")
log_loss = _loss("log_loss")
