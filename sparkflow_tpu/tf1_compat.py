"""Execute TF1 ``MetaGraphDef`` JSON directly — the reference's wire format.

The reference serializes models as ``json_format.MessageToJson(export_meta_graph())``
(``/root/reference/sparkflow/graph_utils.py:6-15``) and every Param/pipeline
carries that string. Round 1 required re-expressing models in the
:mod:`sparkflow_tpu.nn` DSL; this module removes that migration step for
primitive-op graphs: :class:`TF1GraphModel` interprets the ``graph_def`` nodes
with jnp/lax (one function per TF op), exposing the same executable duck-type
as :class:`~sparkflow_tpu.graphdef.GraphModel` (``init`` / ``apply`` /
``loss_vector`` / ordered ``param_specs`` / ``graphdef.resolve``), so
``SparkAsyncDL(tensorflowGraph=<reference metagraph JSON>)`` trains on TPU
with no TensorFlow installed.

Scope: the op set reference models actually produce (dense/conv/pool layers,
elementwise math, reductions, shape plumbing, ``tf.losses``-style loss
subgraphs, random initializers; both ``VariableV2`` (TF≤1.x) and resource
variables (``VarHandleOp``/``ReadVariableOp``)). Exotic ops raise
``NotImplementedError`` naming the op.

Everything is trace-friendly: shape plumbing (``Shape``→``StridedSlice``→
``Fill``...) constant-folds in numpy (static under jit); tensor math runs in
jnp. The loss collection's scalar value is mapped back to a per-example
vector by walking up the reduction subgraph to the last batch-shaped node —
required so padded rows can be masked out (XLA needs static batch shapes).
"""

from __future__ import annotations

import base64
import json
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_DTYPES = {
    "DT_FLOAT": np.float32, "DT_DOUBLE": np.float64, "DT_INT32": np.int32,
    "DT_INT64": np.int64, "DT_BOOL": np.bool_, "DT_HALF": np.float16,
    "DT_BFLOAT16": jnp.bfloat16,
}

_VAR_OPS = ("VarHandleOp", "VariableV2", "Variable")

# suffix marking a Const node that carries CHECKPOINT-RESTORED state baked
# into the graph JSON by bake_nontrainable_values — the marker suppresses the
# fresh-init warning in the evaluator's non-trainable variable fallback
_BAKED_SUFFIX = "/imported_value"

_NP_TO_DT = {np.dtype(np.float32): "DT_FLOAT", np.dtype(np.float64): "DT_DOUBLE",
             np.dtype(np.int32): "DT_INT32", np.dtype(np.int64): "DT_INT64",
             np.dtype(np.bool_): "DT_BOOL", np.dtype(np.float16): "DT_HALF"}


def bake_nontrainable_values(graph_json, values) -> str:
    """Embed restored non-trainable variable values (batch-norm moving
    statistics and the like) into a MetaGraphDef JSON as Const initializers.

    The reference's wire format carries *trainable* variables only
    (``sparkflow/tensorflow_model_loader.py:23-24`` extracts
    ``tf.trainable_variables()``), so a trained BN model round-trips with
    fresh 0/1 moving stats — a shared reference bug this beats. Baking the
    checkpoint tensors into the graph keeps the wire format self-contained:
    the returned JSON serves correctly through the interpreter AND survives
    pipeline persistence with no schema change.

    ``values``: variable node name -> numpy array. Each variable's
    initializer ``Assign`` is re-pointed at a new Const node holding the
    tensor (created if the graph had no Assign for it).
    """
    d = json.loads(graph_json) if isinstance(graph_json, str) else dict(graph_json)
    gd = d.get("graphDef") or d.get("graph_def")
    if gd is None:
        raise ValueError("not a MetaGraphDef JSON (no graphDef)")
    nodes = gd.setdefault("node", [])
    by_name = {n["name"]: n for n in nodes}
    for vname, arr in values.items():
        node = by_name.get(vname)
        if node is None or node["op"] not in _VAR_OPS:
            raise ValueError(f"{vname!r} is not a variable node in this graph")
        arr = np.ascontiguousarray(arr)
        dt = _NP_TO_DT.get(arr.dtype)
        if dt is None:
            raise ValueError(f"{vname!r}: unsupported dtype {arr.dtype}")
        cname = vname + _BAKED_SUFFIX
        const = {
            "name": cname, "op": "Const",
            "attr": {"dtype": {"type": dt},
                     "value": {"tensor": {
                         "dtype": dt,
                         "tensorShape": {"dim": [{"size": str(s)}
                                                 for s in arr.shape]},
                         "tensorContent": base64.b64encode(
                             arr.astype(arr.dtype.newbyteorder("<"))
                             .tobytes()).decode("ascii")}}},
        }
        if cname in by_name:
            by_name[cname].clear()
            by_name[cname].update(const)
        else:
            nodes.append(const)
            by_name[cname] = const
        # re-point the variable's initializer Assign at the baked Const
        assign = next((n for n in nodes
                       if n.get("op") in ("Assign", "AssignVariableOp")
                       and n.get("input", [None])[0].split(":")[0].lstrip("^")
                       == vname), None)
        if assign is not None:
            ins = list(assign["input"])
            ins[1] = cname
            assign["input"] = ins
        else:
            nodes.append({"name": vname + "/imported_assign", "op": "Assign",
                          "input": [vname, cname]})
    return json.dumps(d)


def is_tf1_metagraph(graph_json) -> bool:
    """Cheap sniff: is this (string or parsed dict) a MetaGraphDef JSON?
    The single source of truth for wire-format dispatch (used by
    ``models.model_from_json``)."""
    if isinstance(graph_json, str):
        try:
            graph_json = json.loads(graph_json)
        except (ValueError, TypeError):
            return False
    return (isinstance(graph_json, dict)
            and ("graphDef" in graph_json or "graph_def" in graph_json))


def _b64str(s: str) -> str:
    return base64.b64decode(s).decode("utf-8", errors="replace")


def _parse_variable_name(raw: bytes) -> Optional[str]:
    """Field 1 (variable_name) of a serialized VariableDef proto — minimal
    varint parse, no protobuf schema needed."""
    if not raw or raw[0] != 0x0A:
        return None
    ln, i = 0, 1
    shift = 0
    while i < len(raw):
        b = raw[i]
        ln |= (b & 0x7F) << shift
        i += 1
        shift += 7
        if not b & 0x80:
            break
    return raw[i:i + ln].decode("utf-8", errors="replace")


def _attr_shape(node: dict, key: str = "shape") -> Tuple[int, ...]:
    sh = node.get("attr", {}).get(key, {}).get("shape", {})
    return tuple(int(d.get("size", -1)) for d in sh.get("dim", []))


def _attr_type(node: dict, key: str = "dtype"):
    t = node.get("attr", {}).get(key, {}).get("type", "DT_FLOAT")
    return _DTYPES.get(t, np.float32)


def _parse_const(node: dict):
    t = node["attr"]["value"]["tensor"]
    dtype = _DTYPES.get(t.get("dtype", "DT_FLOAT"), np.float32)
    shape = tuple(int(d.get("size", 0))
                  for d in t.get("tensorShape", {}).get("dim", []))
    if "tensorContent" in t:
        arr = np.frombuffer(base64.b64decode(t["tensorContent"]),
                            dtype=np.dtype(dtype).newbyteorder("<"))
        return arr.reshape(shape).astype(dtype)
    for key, cast in (("floatVal", np.float32), ("doubleVal", np.float64),
                      ("intVal", np.int32), ("int64Val", np.int64),
                      ("boolVal", np.bool_)):
        if key in t:
            vals = np.asarray(t[key], dtype=cast)
            n = int(np.prod(shape)) if shape else max(vals.size, 1)
            if vals.size == 1 and n > 1:
                vals = np.full(n, vals[0], dtype=cast)
            return vals.reshape(shape).astype(dtype)
    return np.zeros(shape, dtype)


def _reduce(fn, x, axes, keepdims):
    if axes is None or (hasattr(axes, "size") and axes.size == 0):
        axes = None
    else:
        axes = tuple(int(a) for a in np.atleast_1d(np.asarray(axes)))
    return fn(x, axis=axes, keepdims=keepdims)


def _is_static(*vals) -> bool:
    return all(isinstance(v, (np.ndarray, np.generic, int, float, bool))
               for v in vals)


class _Names:
    def __init__(self, known):
        self._known = set(known)

    def resolve(self, tensor_name: str) -> str:
        base = tensor_name.split(":")[0]
        if base in self._known:
            return base
        known = ", ".join(sorted(list(self._known))[:20])
        raise KeyError(f"tensor {tensor_name!r} not found in graph; "
                       f"known tensors include: {known}")


class TF1GraphModel:
    """Executable wrapper for a TF1 MetaGraphDef JSON (see module docstring)."""

    # quantized serving trees dequantize at the variable read (weight-only
    # regardless of the requested mode — see _param_value)
    SUPPORTS_INT8_SERVING = True

    def __init__(self, graph_json: str, compute_dtype=None):
        d = json.loads(graph_json) if isinstance(graph_json, str) else graph_json
        gd = d.get("graphDef") or d.get("graph_def") or {}
        self._nodes: Dict[str, dict] = {n["name"]: n for n in gd.get("node", [])}
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if isinstance(compute_dtype, str) else compute_dtype)
        self.graphdef = _Names(self._nodes)

        cd = d.get("collectionDef") or d.get("collection_def") or {}
        self._loss_names = list(
            cd.get("losses", {}).get("nodeList", {}).get("value", []))

        # trainable order straight from the collection (= creation order,
        # exactly tf.trainable_variables — the reference's flat weight order)
        self._var_order: List[str] = []
        tv = cd.get("trainable_variables", {}).get("bytesList", {}).get("value", [])
        for raw in tv:
            name = _parse_variable_name(base64.b64decode(raw))
            if name:
                self._var_order.append(name.split(":")[0])
        if not self._var_order:  # no collection: fall back to node scan order
            self._var_order = [n["name"] for n in gd.get("node", [])
                               if n["op"] in _VAR_OPS]
        self._var_shapes = {}
        for vname in self._var_order:
            node = self._nodes.get(vname)
            if node is None:
                raise ValueError(f"trainable variable {vname!r} has no node")
            self._var_shapes[vname] = _attr_shape(node)

        # params are grouped scope/leaf ONLY when scopes appear contiguously
        # in creation order — otherwise grouping would silently permute the
        # flat wire order away from tf.trainable_variables (e.g. reopened
        # variable scopes). Interleaved scopes fall back to one layer per
        # variable, which preserves the flat order unconditionally.
        scopes_seen: List[str] = []
        self._grouped = True
        for vname in self._var_order:
            scope = vname.rsplit("/", 1)[0] if "/" in vname else vname
            if scope in scopes_seen and scopes_seen[-1] != scope:
                self._grouped = False
                break
            if not scopes_seen or scopes_seen[-1] != scope:
                scopes_seen.append(scope)

        # assign node per variable (for init-value subgraph evaluation).
        # Recorded for EVERY variable node, not just trainables: non-trainable
        # variables (batch-norm moving stats) are read via their initializer.
        self._var_init = {}
        for n in self._nodes.values():
            if n["op"] in ("Assign", "AssignVariableOp"):
                ins = n.get("input", [])
                if len(ins) >= 2:
                    target = ins[0].split(":")[0].lstrip("^")
                    tnode = self._nodes.get(target)
                    if (tnode is not None and tnode["op"] in _VAR_OPS
                            and target not in self._var_init):
                        self._var_init[target] = ins[1]

    # -- GraphModel duck type -------------------------------------------------

    def nontrainable_variables(self) -> List[str]:
        """Variable nodes outside the trainable collection (batch-norm moving
        statistics etc.) — the state :func:`bake_nontrainable_values` can
        restore from a checkpoint."""
        trainable = set(self._var_order)
        return [n["name"] for n in self._nodes.values()
                if n["op"] in _VAR_OPS and n["name"] not in trainable]

    def _param_key(self, vname: str) -> Tuple[str, str]:
        if self._grouped and "/" in vname:
            return vname.rsplit("/", 1)
        return vname, "value"

    def param_specs(self):
        """Ordered specs; flattening them yields EXACTLY the trainable
        collection order (= ``tf.trainable_variables``, the reference's flat
        wire format)."""
        specs: Dict[str, Dict[str, tuple]] = {}
        for vname in self._var_order:
            scope, leaf = self._param_key(vname)
            specs.setdefault(scope, {})[leaf] = (self._var_shapes[vname], "zeros")
        return specs

    def _param_value(self, params, vname: str):
        scope, leaf = self._param_key(vname)
        layer = params[scope]
        if leaf not in layer and f"{leaf}_q8" in layer:
            # int8-quantized serving tree (utils/quant.py): TF1 graphs
            # dequantize at the variable read — weight-only semantics, so
            # every downstream op is untouched (the interpreter can't know
            # which consumer is a matmul, so the dynamic int8 path doesn't
            # apply here)
            from .utils.quant import dequantize_tensor
            return dequantize_tensor(layer[f"{leaf}_q8"],
                                     layer[f"{leaf}_scale"])
        return layer[leaf]

    def init(self, rng):
        params: Dict[str, Dict[str, Any]] = {}
        for vname in self._var_order:
            rng, sub = jax.random.split(rng)
            init_node = self._var_init.get(vname)
            if init_node is not None:
                ev = _Evaluator(self, params={}, feeds={}, train=False, rng=sub)
                val = jnp.asarray(ev.value(init_node))
            else:
                val = jnp.zeros(self._var_shapes[vname], jnp.float32)
            scope, leaf = self._param_key(vname)
            params.setdefault(scope, {})[leaf] = val
        return params

    def quantize_for_serving(self, params, mode: str = "weight_only",
                             min_size: int = 4096):
        """int8-quantize a trained params tree for inference
        (``utils/quant.py``). TF1 graphs always serve weight-only — the
        interpreter dequantizes at the variable read, so a 'dynamic'
        request is accepted but behaves as weight-only."""
        from .utils.quant import quantize_for_serving
        return quantize_for_serving(self, params, mode, min_size)

    def apply(self, params, feeds: Dict[str, Any], outputs: Sequence[str],
              train: bool = False, rng=None) -> Dict[str, Any]:
        ev = _Evaluator(self, params, feeds, train, rng)
        return {o: jnp.asarray(ev.value(o)) for o in outputs}

    def loss_vector(self, params, feeds: Dict[str, Any], train: bool = True,
                    rng=None):
        if not self._loss_names:
            raise ValueError("metagraph has no losses collection "
                             "(tf.GraphKeys.LOSSES) — reference contract")
        target = self._per_example_loss_node(self._loss_names[0].split(":")[0])
        ev = _Evaluator(self, params, feeds, train, rng)
        val = jnp.asarray(ev.value(target))
        # EVERY additional losses-collection entry contributes (the usual
        # pattern: add_to_collection(LOSSES, weight_decay)); scalars spread
        # per-example, batch-shaped entries reduce per-example
        for name in self._loss_names[1:]:
            extra = jnp.asarray(ev.value(name.split(":")[0]))
            if extra.ndim > 1:
                extra = jnp.mean(extra.reshape(extra.shape[0], -1), axis=-1)
            val = val + extra
        if val.ndim == 0:
            # irreducibly scalar loss: broadcast (padding correctness is then
            # the caller's concern; reference losses all pass the walk above)
            b = None
            for v in feeds.values():
                if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1:
                    b = v.shape[0]
                    break
            return jnp.full((b or 1,), val)
        if val.ndim > 1:
            val = jnp.mean(val.reshape(val.shape[0], -1), axis=-1)
        return val

    def _node_batch_shaped(self, name: str) -> bool:
        node = self._nodes.get(name)
        if node is None:
            return False
        shapes = (node.get("attr", {}).get("_output_shapes", {})
                  .get("list", {}).get("shape", []))
        if not shapes:
            return False
        dims = shapes[0].get("dim", [])
        return bool(dims) and int(dims[0].get("size", 0)) == -1
    def _per_example_loss_node(self, name: str) -> str:
        """Walk up scalar-reduction plumbing (DivNoNan/Sum/Mean/Mul/weights)
        to the last node that still carries the batch dimension."""
        seen = 0
        cur = name
        while not self._node_batch_shaped(cur) and seen < 32:
            node = self._nodes.get(cur)
            if node is None or node["op"] not in (
                    "DivNoNan", "RealDiv", "Sum", "Mean", "Mul", "Identity",
                    "Neg", "AddV2", "Add", "Squeeze"):
                break
            ins = [i for i in node.get("input", []) if not i.startswith("^")]
            if not ins:
                break
            # prefer a batch-shaped input; else follow input 0
            nxt = None
            for i in ins:
                if self._node_batch_shaped(i.split(":")[0]):
                    nxt = i.split(":")[0]
                    break
            cur = nxt if nxt is not None else ins[0].split(":")[0]
            seen += 1
        return cur

    def cast(self, x):
        if self.compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x


class _Evaluator:
    """Memoized single-pass interpreter over graph_def nodes."""

    def __init__(self, model: TF1GraphModel, params, feeds, train, rng):
        self.m = model
        self.params = params
        self.feeds = {k.split(":")[0]: v for k, v in (feeds or {}).items()}
        self.train = train
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.cache: Dict[str, Any] = {}

    # -- plumbing ------------------------------------------------------------

    def value(self, ref: str):
        name, idx = (ref.split(":") + ["0"])[:2] if ":" in ref else (ref, "0")
        out = self._node_value(name)
        if isinstance(out, tuple):
            return out[int(idx)]
        return out

    def _in(self, node, i):
        return self.value(node["input"][i].lstrip("^"))

    def _ins(self, node):
        return [self.value(i) for i in node.get("input", [])
                if not i.startswith("^")]

    def _node_value(self, name: str):
        if name in self.cache:
            return self.cache[name]
        node = self.m._nodes.get(name)
        if node is None:
            raise KeyError(f"no node named {name!r} in graph")
        val = self._eval(node)
        self.cache[name] = val
        return val

    def _next_rng(self):
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def _compute_cast(self, x):
        """MXU-feeding operands honor the model's compute_dtype (bf16 on
        TPU, one policy: TF1GraphModel.cast); accumulation stays f32 via
        preferred_element_type."""
        return self.m.cast(jnp.asarray(x))

    # -- op table ------------------------------------------------------------

    def _eval(self, node):  # noqa: C901 — one dispatch table, kept flat
        op = node["op"]
        attr = node.get("attr", {})

        if op == "Placeholder":
            base = node["name"]
            if base in self.feeds:
                return jnp.asarray(self.feeds[base])
            raise KeyError(f"placeholder {base!r} not fed; feeds: "
                           f"{sorted(self.feeds)}")
        if op == "PlaceholderWithDefault":
            base = node["name"]
            if base in self.feeds:
                return jnp.asarray(self.feeds[base])
            return self._in(node, 0)
        if op == "Const":
            return _parse_const(node)
        if op in _VAR_OPS:
            name = node["name"]
            if name in self.m._var_shapes:
                return self.m._param_value(self.params, name)
            # non-trainable variable (e.g. batch-norm moving_mean/variance):
            # not in the trainable collection, so it has no params slot —
            # evaluate its initializer subgraph instead. Checkpoint imports
            # bake restored values in as `<var>/imported_value` Consts
            # (bake_nontrainable_values); WITHOUT a baked value the
            # initializer yields FRESH-INIT state (0/1), not whatever the
            # source graph learned — warn in that case only
            init_node = self.m._var_init.get(name)
            if init_node is None or not init_node.endswith(_BAKED_SUFFIX):
                import warnings
                warnings.warn(
                    f"reading non-trainable variable {name!r} via its "
                    f"initializer subgraph (the reference wire format "
                    f"carries trainable variables only); if this model "
                    f"relies on learned non-trainable state (e.g. batch-norm "
                    f"moving statistics), those values are fresh-initialized "
                    f"here — import through load_tensorflow_model to restore "
                    f"them from the checkpoint", stacklevel=2)
            if init_node is not None:
                return self.value(init_node)
            shape = _attr_shape(node)
            return jnp.zeros(shape, _attr_type(node))
        if op in ("ReadVariableOp", "Identity", "StopGradient", "Snapshot",
                  "PreventGradient", "CheckNumerics", "EnsureShape"):
            return self._in(node, 0)
        if op == "NoOp":
            return None

        # --- binary/unary elementwise: (numpy fn, jnp fn) pairs — the numpy
        # path constant-folds shape plumbing so it stays STATIC under jit
        # (jnp on static values would stage a traced op)
        binary = {
            "AddV2": (np.add, jnp.add), "Add": (np.add, jnp.add),
            "Sub": (np.subtract, jnp.subtract),
            "Mul": (np.multiply, jnp.multiply),
            "RealDiv": (np.divide, jnp.divide), "Div": (np.divide, jnp.divide),
            "Maximum": (np.maximum, jnp.maximum),
            "Minimum": (np.minimum, jnp.minimum),
            "SquaredDifference": (lambda a, b: np.square(a - b),
                                  lambda a, b: jnp.square(a - b)),
            "Pow": (np.power, jnp.power),
            "FloorDiv": (np.floor_divide, jnp.floor_divide),
            "Equal": (np.equal, jnp.equal), "NotEqual": (np.not_equal, jnp.not_equal),
            "Greater": (np.greater, jnp.greater),
            "GreaterEqual": (np.greater_equal, jnp.greater_equal),
            "Less": (np.less, jnp.less), "LessEqual": (np.less_equal, jnp.less_equal),
            "LogicalAnd": (np.logical_and, jnp.logical_and),
            "LogicalOr": (np.logical_or, jnp.logical_or),
            "FloorMod": (np.mod, jnp.mod),
            "Mod": (np.fmod, jnp.fmod),
            "TruncateMod": (np.fmod, jnp.fmod),
            "Atan2": (np.arctan2, jnp.arctan2),
        }
        if op in binary:
            a, b = self._in(node, 0), self._in(node, 1)
            np_fn, jnp_fn = binary[op]
            if _is_static(a, b):
                return np.asarray(np_fn(a, b))
            return jnp_fn(jnp.asarray(a), jnp.asarray(b))
        if op == "DivNoNan":
            a, b = jnp.asarray(self._in(node, 0)), jnp.asarray(self._in(node, 1))
            return jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b))
        unary = {
            "Neg": (np.negative, jnp.negative), "Log": (np.log, jnp.log),
            "Log1p": (np.log1p, jnp.log1p), "Exp": (np.exp, jnp.exp),
            "Sqrt": (np.sqrt, jnp.sqrt),
            "Rsqrt": (lambda x: 1 / np.sqrt(x), lambda x: 1 / jnp.sqrt(x)),
            "Square": (np.square, jnp.square), "Abs": (np.abs, jnp.abs),
            "Sign": (np.sign, jnp.sign), "Floor": (np.floor, jnp.floor),
            "Ceil": (np.ceil, jnp.ceil), "Round": (np.round, jnp.round),
            "Sigmoid": (None, jax.nn.sigmoid), "Tanh": (np.tanh, jnp.tanh),
            "Relu": (lambda x: np.maximum(x, 0), jax.nn.relu),
            "Relu6": (lambda x: np.clip(x, 0, 6), lambda x: jnp.clip(x, 0, 6)),
            "Elu": (None, jax.nn.elu), "Selu": (None, jax.nn.selu),
            "Softplus": (None, jax.nn.softplus),
            "LogicalNot": (np.logical_not, jnp.logical_not),
            "Erf": (None, jax.scipy.special.erf),
            "IsFinite": (np.isfinite, jnp.isfinite),
            "ZerosLike": (np.zeros_like, jnp.zeros_like),
            "OnesLike": (np.ones_like, jnp.ones_like),
            "Reciprocal": (lambda x: 1 / x, lambda x: 1 / x),
            "Inv": (lambda x: 1 / x, lambda x: 1 / x),
            "Sin": (np.sin, jnp.sin), "Cos": (np.cos, jnp.cos),
            "Tan": (np.tan, jnp.tan), "Atan": (np.arctan, jnp.arctan),
            "Expm1": (np.expm1, jnp.expm1),
            "Softsign": (None, jax.nn.soft_sign),
        }
        if op in unary:
            x = self._in(node, 0)
            np_fn, jnp_fn = unary[op]
            if np_fn is not None and _is_static(x):
                return np.asarray(np_fn(x))
            return jnp_fn(jnp.asarray(x))
        if op == "Cast":
            return jnp.asarray(self._in(node, 0)).astype(
                _attr_type(node, "DstT"))
        if op == "Select" or op == "SelectV2":
            c, a, b = self._ins(node)
            return jnp.where(jnp.asarray(c), jnp.asarray(a), jnp.asarray(b))
        if op == "ClipByValue":
            x, lo, hi = self._ins(node)
            return jnp.clip(jnp.asarray(x), lo, hi)

        # --- linear algebra / nn ---
        if op in ("Conv2D", "MaxPool", "AvgPool", "BiasAdd",
                  "DepthwiseConv2dNative"):
            fmt = attr.get("data_format", {}).get("s")
            if fmt and _b64str(fmt) not in ("NHWC", ""):
                raise NotImplementedError(
                    f"TF1 op {op!r} with data_format={_b64str(fmt)!r} "
                    f"(node {node['name']!r}): only NHWC is supported")
        if op == "MatMul":
            a, b = (self._compute_cast(self._in(node, 0)),
                    self._compute_cast(self._in(node, 1)))
            if attr.get("transpose_a", {}).get("b"):
                a = a.T
            if attr.get("transpose_b", {}).get("b"):
                b = b.T
            # bf16 operands on the MXU, f32 accumulation
            return jnp.matmul(a, b, preferred_element_type=jnp.float32)
        if op == "BiasAdd":
            return jnp.asarray(self._in(node, 0)) + jnp.asarray(self._in(node, 1))
        if op == "Softmax":
            return jax.nn.softmax(jnp.asarray(self._in(node, 0)), axis=-1)
        if op == "LogSoftmax":
            return jax.nn.log_softmax(jnp.asarray(self._in(node, 0)), axis=-1)
        if op == "SoftmaxCrossEntropyWithLogits":
            logits = jnp.asarray(self._in(node, 0))
            labels = jnp.asarray(self._in(node, 1))
            logp = jax.nn.log_softmax(logits, axis=-1)
            loss = -jnp.sum(labels * logp, axis=-1)
            grad = jax.nn.softmax(logits, axis=-1) - labels
            return (loss, grad)
        if op == "Conv2D":
            x, k = (self._compute_cast(self._in(node, 0)),
                    self._compute_cast(self._in(node, 1)))
            strides = [int(s) for s in attr["strides"]["list"]["i"]]
            padding = _b64str(attr["padding"]["s"])
            return jax.lax.conv_general_dilated(
                x, k, window_strides=strides[1:3], padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32)
        if op == "MaxPool":
            x = jnp.asarray(self._in(node, 0))
            ks = [int(s) for s in attr["ksize"]["list"]["i"]]
            st = [int(s) for s in attr["strides"]["list"]["i"]]
            padding = _b64str(attr["padding"]["s"])
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, ks, st,
                                         padding)
        if op == "AvgPool":
            x = jnp.asarray(self._in(node, 0))
            ks = [int(s) for s in attr["ksize"]["list"]["i"]]
            st = [int(s) for s in attr["strides"]["list"]["i"]]
            padding = _b64str(attr["padding"]["s"])
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, ks, st, padding)
            ones = jnp.ones_like(x)
            c = jax.lax.reduce_window(ones, 0.0, jax.lax.add, ks, st, padding)
            return s / c

        if op == "LeakyRelu":
            alpha = float(attr.get("alpha", {}).get("f", 0.2))
            return jax.nn.leaky_relu(jnp.asarray(self._in(node, 0)),
                                     negative_slope=alpha)
        if op == "AddN":
            vals = self._ins(node)
            if _is_static(*vals):
                return np.asarray(sum(np.asarray(v) for v in vals))
            out = jnp.asarray(vals[0])
            for v in vals[1:]:
                out = out + jnp.asarray(v)
            return out
        if op == "SparseSoftmaxCrossEntropyWithLogits":
            logits = jnp.asarray(self._in(node, 0))
            labels = jnp.asarray(self._in(node, 1)).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            loss = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
            grad = (jax.nn.softmax(logits, axis=-1)
                    - jax.nn.one_hot(labels, logits.shape[-1],
                                     dtype=logits.dtype))
            return (loss, grad)
        if op == "OneHot":
            indices = self._in(node, 0)
            depth = int(np.asarray(self._in(node, 1)))
            on_v = self._in(node, 2)
            off_v = self._in(node, 3)
            axis = int(attr.get("axis", {}).get("i", -1))
            ind = jnp.asarray(indices).astype(jnp.int32)
            oh = jax.nn.one_hot(ind, depth, axis=axis)
            on_v, off_v = jnp.asarray(on_v), jnp.asarray(off_v)
            return (oh * (on_v - off_v) + off_v).astype(on_v.dtype)
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            x = jnp.asarray(self._in(node, 0))
            scale = jnp.asarray(self._in(node, 1))
            offset = jnp.asarray(self._in(node, 2))
            eps = float(attr.get("epsilon", {}).get("f", 1e-3))
            training = bool(attr.get("is_training", {}).get("b", True))
            fmt = attr.get("data_format", {}).get("s")
            if fmt and _b64str(fmt) not in ("NHWC", ""):
                raise NotImplementedError(
                    f"{op} with data_format={_b64str(fmt)!r}: NHWC only")
            if training:
                axes = tuple(range(x.ndim - 1))
                mean = jnp.mean(x, axis=axes)
                var = jnp.var(x, axis=axes)
            else:
                mean = jnp.asarray(self._in(node, 3))
                var = jnp.asarray(self._in(node, 4))
            inv = jax.lax.rsqrt(var + eps)
            y = (x - mean) * inv * scale + offset
            # outputs: y, batch_mean, batch_var(, reserved...) — reserved
            # slots mirror the stats, enough for any consumer on the value path
            return (y, mean, var, mean, var, var)
        if op in ("BatchMatMul", "BatchMatMulV2"):
            a = self._compute_cast(self._in(node, 0))
            b = self._compute_cast(self._in(node, 1))
            if attr.get("adj_x", {}).get("b"):
                a = jnp.swapaxes(a, -1, -2)
            if attr.get("adj_y", {}).get("b"):
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b, preferred_element_type=jnp.float32)
        if op == "DepthwiseConv2dNative":
            x = self._compute_cast(self._in(node, 0))
            k = self._compute_cast(self._in(node, 1))  # [H, W, C, M]
            strides = [int(s) for s in attr["strides"]["list"]["i"]]
            padding = _b64str(attr["padding"]["s"])
            dil = [int(d) for d in attr.get("dilations", {})
                   .get("list", {}).get("i", [1, 1, 1, 1])]
            h, w, c, m = k.shape
            # grouped conv: one group per input channel, kernel [H, W, 1, C*M]
            k = jnp.reshape(k, (h, w, 1, c * m))
            return jax.lax.conv_general_dilated(
                x, k, window_strides=strides[1:3], padding=padding,
                rhs_dilation=dil[1:3],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=c,
                preferred_element_type=jnp.float32)
        if op == "LRN":
            x = jnp.asarray(self._in(node, 0))
            radius = int(attr.get("depth_radius", {}).get("i", 5))
            bias = float(attr.get("bias", {}).get("f", 1.0))
            alpha = float(attr.get("alpha", {}).get("f", 1.0))
            beta = float(attr.get("beta", {}).get("f", 0.5))
            sq = jnp.square(x)
            win = 2 * radius + 1
            sq_sum = jax.lax.reduce_window(
                sq, 0.0, jax.lax.add, (1, 1, 1, win), (1, 1, 1, 1), "SAME")
            return x / jnp.power(bias + alpha * sq_sum, beta)
        if op == "Cumsum":
            x = jnp.asarray(self._in(node, 0))
            axis = int(np.asarray(self._in(node, 1)))
            exclusive = bool(attr.get("exclusive", {}).get("b", False))
            reverse = bool(attr.get("reverse", {}).get("b", False))
            if reverse:
                x = jnp.flip(x, axis)
            out = jnp.cumsum(x, axis=axis)
            if exclusive:
                out = out - x
            if reverse:
                out = jnp.flip(out, axis)
            return out
        if op == "TopKV2":
            x = jnp.asarray(self._in(node, 0))
            k = int(np.asarray(self._in(node, 1)))
            vals, idx = jax.lax.top_k(x, k)
            return (vals, idx.astype(jnp.int32))
        if op in ("Split", "SplitV", "Unpack"):
            if op == "Unpack":
                x = jnp.asarray(self._in(node, 0))
                axis = int(attr.get("axis", {}).get("i", 0))
                n = int(attr.get("num", {}).get("i", x.shape[axis]))
                parts = jnp.split(x, n, axis=axis)
                return tuple(jnp.squeeze(p, axis=axis) for p in parts)
            if op == "Split":  # inputs: (axis, value)
                axis = int(np.asarray(self._in(node, 0)))
                x = jnp.asarray(self._in(node, 1))
                n = int(attr.get("num_split", {}).get("i", 1))
                return tuple(jnp.split(x, n, axis=axis))
            # SplitV: (value, size_splits, axis)
            x = jnp.asarray(self._in(node, 0))
            sizes = [int(s) for s in np.asarray(self._in(node, 1)).reshape(-1)]
            axis = int(np.asarray(self._in(node, 2)))
            if sizes.count(-1) > 1:
                raise NotImplementedError(
                    f"SplitV (node {node['name']!r}): more than one inferred "
                    f"(-1) entry in size_splits {sizes}")
            if -1 in sizes:  # one entry may be inferred from the dim size
                rest = sum(s for s in sizes if s != -1)
                sizes[sizes.index(-1)] = int(x.shape[axis]) - rest
            bounds = np.cumsum(sizes)[:-1].tolist()
            return tuple(jnp.split(x, bounds, axis=axis))
        if op in ("SpaceToBatchND", "BatchToSpaceND"):
            # the lowering TF emits for dilated (atrous) convolutions
            x = jnp.asarray(self._in(node, 0))
            block = [int(b) for b in np.asarray(self._in(node, 1)).reshape(-1)]
            pc = np.asarray(self._in(node, 2)).reshape(-1, 2)
            m = len(block)
            rest = list(x.shape[1 + m:])
            if op == "SpaceToBatchND":
                pads = ([(0, 0)] + [(int(a), int(b)) for a, b in pc]
                        + [(0, 0)] * len(rest))
                x = jnp.pad(x, pads)
                batch, spatial = x.shape[0], x.shape[1:1 + m]
                shape = [batch]
                for d, b in zip(spatial, block):
                    shape += [d // b, b]
                x = jnp.reshape(x, shape + rest)
                perm = ([2 * i + 2 for i in range(m)] + [0]
                        + [2 * i + 1 for i in range(m)]
                        + [2 * m + 1 + i for i in range(len(rest))])
                x = jnp.transpose(x, perm)
                return jnp.reshape(
                    x, [batch * int(np.prod(block))]
                    + [spatial[i] // block[i] for i in range(m)] + rest)
            batch, spatial = x.shape[0], x.shape[1:1 + m]
            prod_b = int(np.prod(block))
            x = jnp.reshape(x, list(block) + [batch // prod_b]
                            + list(spatial) + rest)
            perm = [m]
            for i in range(m):
                perm += [m + 1 + i, i]
            perm += [2 * m + 1 + i for i in range(len(rest))]
            x = jnp.transpose(x, perm)
            x = jnp.reshape(x, [batch // prod_b]
                            + [spatial[i] * block[i] for i in range(m)] + rest)
            idx = [slice(None)]
            for i in range(m):
                c0, c1 = int(pc[i][0]), int(pc[i][1])
                idx.append(slice(c0, x.shape[1 + i] - c1 if c1 else None))
            return x[tuple(idx + [slice(None)] * len(rest))]
        if op in ("Print", "PrintV2", "Assert"):
            # debug/validation side-effects: pass through / no-op on the
            # value path (Assert appears only as a control dependency)
            return self._in(node, 0) if node.get("input") else None

        # --- reductions / indexing ---
        reductions = {"Sum": jnp.sum, "Mean": jnp.mean, "Max": jnp.max,
                      "Min": jnp.min, "Prod": jnp.prod, "All": jnp.all,
                      "Any": jnp.any}
        if op in reductions:
            x = self._in(node, 0)
            axes = self._in(node, 1)
            keep = bool(attr.get("keep_dims", {}).get("b", False))
            if _is_static(x, axes):
                return np.asarray(_reduce(getattr(np, reductions[op].__name__),
                                          np.asarray(x), axes, keep))
            return _reduce(reductions[op], jnp.asarray(x), np.asarray(axes), keep)
        if op in ("ArgMax", "ArgMin"):
            x = jnp.asarray(self._in(node, 0))
            axis = int(np.asarray(self._in(node, 1)))
            fn = jnp.argmax if op == "ArgMax" else jnp.argmin
            return fn(x, axis=axis).astype(_attr_type(node, "output_type"))

        # --- shapes (static: numpy) ---
        if op == "Shape":
            x = self._in(node, 0)
            return np.asarray(np.shape(x), np.int32)
        if op == "Size":
            return np.asarray(np.size(self._in(node, 0)), np.int32)
        if op == "Rank":
            return np.asarray(np.ndim(self._in(node, 0)), np.int32)
        if op == "Reshape":
            x = self._in(node, 0)
            shape = [int(s) for s in np.asarray(self._in(node, 1)).reshape(-1)]
            return jnp.reshape(jnp.asarray(x), shape)
        if op == "ExpandDims":
            return jnp.expand_dims(jnp.asarray(self._in(node, 0)),
                                   int(np.asarray(self._in(node, 1))))
        if op == "Squeeze":
            dims = [int(i) for i in attr.get("squeeze_dims", {})
                    .get("list", {}).get("i", [])]
            x = jnp.asarray(self._in(node, 0))
            return jnp.squeeze(x, axis=tuple(dims) if dims else None)
        if op == "Fill":
            dims = [int(d) for d in np.asarray(self._in(node, 0)).reshape(-1)]
            v = self._in(node, 1)
            if _is_static(v):
                return np.full(dims, np.asarray(v))
            return jnp.full(dims, v)
        if op == "Range":
            s, l, d = (np.asarray(self._in(node, i)) for i in range(3))
            return np.arange(int(s), int(l), int(d), dtype=np.int32)
        if op == "Pack":
            vals = self._ins(node)
            axis = int(attr.get("axis", {}).get("i", 0))
            if _is_static(*vals):
                return np.stack([np.asarray(v) for v in vals], axis=axis)
            return jnp.stack([jnp.asarray(v) for v in vals], axis=axis)
        if op == "ConcatV2":
            vals = self._ins(node)
            axis = int(np.asarray(vals[-1]))
            parts = vals[:-1]
            if _is_static(*parts):
                return np.concatenate([np.asarray(v) for v in parts], axis)
            return jnp.concatenate([jnp.asarray(v) for v in parts], axis)
        if op == "Tile":
            x = jnp.asarray(self._in(node, 0))
            reps = [int(r) for r in np.asarray(self._in(node, 1)).reshape(-1)]
            return jnp.tile(x, reps)
        if op == "Transpose":
            x = jnp.asarray(self._in(node, 0))
            perm = [int(p) for p in np.asarray(self._in(node, 1)).reshape(-1)]
            return jnp.transpose(x, perm)
        if op == "StridedSlice":
            x = self._in(node, 0)
            begin = np.asarray(self._in(node, 1)).reshape(-1)
            end = np.asarray(self._in(node, 2)).reshape(-1)
            strides = np.asarray(self._in(node, 3)).reshape(-1)
            bm = int(attr.get("begin_mask", {}).get("i", 0))
            em = int(attr.get("end_mask", {}).get("i", 0))
            sm = int(attr.get("shrink_axis_mask", {}).get("i", 0))
            em_ellipsis = int(attr.get("ellipsis_mask", {}).get("i", 0))
            nm = int(attr.get("new_axis_mask", {}).get("i", 0))
            if em_ellipsis or nm:
                raise NotImplementedError(
                    "StridedSlice ellipsis/new-axis masks not supported")
            idx = []
            for i in range(len(begin)):
                if sm & (1 << i):
                    idx.append(int(begin[i]))
                    continue
                b = None if bm & (1 << i) else int(begin[i])
                e = None if em & (1 << i) else int(end[i])
                idx.append(slice(b, e, int(strides[i])))
            out = np.asarray(x)[tuple(idx)] if _is_static(x) \
                else jnp.asarray(x)[tuple(idx)]
            return out
        if op == "L2Loss":
            x = jnp.asarray(self._in(node, 0))
            return jnp.sum(jnp.square(x)) / 2.0
        if op in ("Pad", "PadV2"):
            x = jnp.asarray(self._in(node, 0))
            paddings = [(int(a), int(b))
                        for a, b in np.asarray(self._in(node, 1))]
            cval = (float(np.asarray(self._in(node, 2)))
                    if op == "PadV2" else 0.0)
            return jnp.pad(x, paddings, constant_values=cval)
        if op == "Slice":
            x = self._in(node, 0)
            begin = [int(b) for b in np.asarray(self._in(node, 1)).reshape(-1)]
            size = [int(s) for s in np.asarray(self._in(node, 2)).reshape(-1)]
            idx = tuple(slice(b, None if s == -1 else b + s)
                        for b, s in zip(begin, size))
            return (np.asarray(x)[idx] if _is_static(x)
                    else jnp.asarray(x)[idx])
        if op == "GatherV2":
            x = jnp.asarray(self._in(node, 0))
            ind = jnp.asarray(self._in(node, 1)).astype(jnp.int32)
            axis = int(np.asarray(self._in(node, 2)))
            return jnp.take(x, ind, axis=axis)
        if op == "ResourceGather":  # embedding_lookup on a resource variable
            x = jnp.asarray(self._in(node, 0))
            ind = jnp.asarray(self._in(node, 1)).astype(jnp.int32)
            return jnp.take(x, ind, axis=0)
        if op == "BroadcastTo":
            x = jnp.asarray(self._in(node, 0))
            shape = [int(s) for s in np.asarray(self._in(node, 1)).reshape(-1)]
            return jnp.broadcast_to(x, shape)

        # --- random (initializers, dropout) ---
        if op == "RandomUniform":
            shape = [int(s) for s in np.asarray(self._in(node, 0)).reshape(-1)]
            return jax.random.uniform(self._next_rng(), shape, jnp.float32)
        if op in ("RandomStandardNormal", "RandomNormal"):
            shape = [int(s) for s in np.asarray(self._in(node, 0)).reshape(-1)]
            return jax.random.normal(self._next_rng(), shape, jnp.float32)
        if op == "TruncatedNormal":
            shape = [int(s) for s in np.asarray(self._in(node, 0)).reshape(-1)]
            return jax.random.truncated_normal(self._next_rng(), -2.0, 2.0,
                                               shape, jnp.float32)

        raise NotImplementedError(
            f"TF1 op {op!r} (node {node['name']!r}) is not supported by the "
            f"tf1_compat interpreter; rebuild this model with sparkflow_tpu.nn")
