"""Online serving: the request/response half of the framework.

The reference's only inference path is the offline Spark batch transform
(``sparkflow/ml_util.py:54-83`` via ``SparkAsyncDLModel._transform``); its one
online process is the *training-side* driver-hosted Flask parameter server
(``sparkflow/HogwildSparkModel.py:156-166``). This package is the serving-side
analogue the ROADMAP north star ("serves heavy traffic from millions of
users") requires:

- :class:`~sparkflow_tpu.serving.engine.InferenceEngine` — loads a trained
  model, AOT-compiles (``jit(...).lower().compile()``) the apply function for
  a ladder of padded batch-size buckets so steady-state serving never
  recompiles, shards batches over a ``dp`` mesh, serves int8
  (``utils.quant``) when asked.
- :class:`~sparkflow_tpu.serving.batcher.MicroBatcher` — coalesces concurrent
  requests under a deadline into one device batch (the SparkNet lever,
  arXiv:1511.06051: amortize fixed per-call overhead by batching before the
  accelerator), with bounded-queue backpressure
  (:class:`~sparkflow_tpu.serving.batcher.QueueFull`).
- :class:`~sparkflow_tpu.serving.server.InferenceServer` /
  :class:`~sparkflow_tpu.serving.client.ServingClient` — a stdlib JSON-HTTP
  front (``/v1/predict``, ``/healthz``, ``/metrics``) and its tiny client.
  The server carries the ``resilience.lifecycle`` state machine: SIGTERM (or
  ``drain()``) finishes in-flight requests while new ones get ``503`` +
  ``Retry-After`` (:class:`~sparkflow_tpu.serving.batcher.Draining`), and
  the client retries 503s/connection errors with jittered backoff.

- :class:`~sparkflow_tpu.serving.router.RouterServer` /
  :class:`~sparkflow_tpu.serving.membership.Membership` — the fleet layer:
  N replicas behind one router doing health-gated membership (periodic
  ``/healthz`` probes + per-replica circuit breakers with half-open
  recovery), least-loaded dispatch, token-bucket admission and in-flight
  shedding on the same ``queue_full`` 503 path, retry/reroute around dead
  or draining replicas, opt-in hedged requests with loser cancellation,
  and an opt-in content-addressed result cache. Same wire protocol as a
  single replica, so clients point at a fleet unchanged.

- :class:`~sparkflow_tpu.serving.decode.DecodeEngine` /
  :class:`~sparkflow_tpu.serving.batcher.ContinuousBatcher` — the
  autoregressive decode path: a paged KV cache
  (:class:`~sparkflow_tpu.serving.kvcache.PagedKVCache`, fixed-size pages +
  per-slot page tables over one preallocated pool, consumed directly by the
  pallas ``paged_attention`` kernel), AOT-compiled prefill buckets and a
  fixed-shape decode step that never recompiles, and continuous batching —
  sequences join and leave the decode batch at token boundaries, so a short
  completion never waits for a long one. Served as ``POST /v1/generate``
  (pass the batcher to ``InferenceServer(generate_batcher=...)``) with the
  same backpressure, drain, and request-id contract as predict.

- :class:`~sparkflow_tpu.serving.autoscaler.Autoscaler` /
  :class:`~sparkflow_tpu.serving.autoscaler.ReplicaManager` — the
  self-healing elastic fleet: a daemon that reads queue-wait p95 and
  per-replica capacity gauges, feeds them to the pure
  :func:`~sparkflow_tpu.serving.policies.scale_decision` (hysteresis
  bands + cooldowns, tuned in ``sparkflow_tpu.sim``), and spawns /
  SIGTERM-drains real replica processes, replacing crashed ones within
  one tick of exit-code reaping.
  :class:`~sparkflow_tpu.serving.coldstart.ExecutableStore` makes the
  ordered capacity arrive fast: serialized XLA executables stored next
  to the weights boot a replica with zero compiles.

- :class:`~sparkflow_tpu.serving.weightstore.WeightStore` /
  :class:`~sparkflow_tpu.serving.weightstore.WeightWatcher` — live weight
  publication: immutable, monotonically versioned weight sets published
  crash-consistently (tmp dir + sha256 manifest + atomic rename), watched
  by replicas that verify and hot-swap at a drained batch/token boundary
  (double-buffered, zero retraces, never mixing versions in one request).
  ``RouterServer(canary=True)`` adds version-aware canary dispatch with a
  health gate (:class:`~sparkflow_tpu.serving.router.CanaryController`)
  that promotes a healthy new version or instantly quarantines and rolls
  back a bad one — a corrupt or regressing publish never takes traffic.

See ``docs/serving.md``, ``docs/resilience.md``, and
``examples/serving_example.py``; ``make fleet-smoke`` chaos-tests the
router + replicas end to end; ``make decode-smoke`` does the same for
continuous-batching generation.
"""

from . import policies
from .autoscaler import Autoscaler, ReplicaManager
from .batcher import ContinuousBatcher, Draining, MicroBatcher, QueueFull
from .client import ConnectionPool, ServingClient, ServingError
from .coldstart import ExecutableStore
from .decode import DecodeEngine
from .engine import InferenceEngine
from .kvcache import OutOfPages, PagedKVCache
from .membership import BreakerState, CircuitBreaker, Membership, Replica
from .policies import ReplicaView, VersionStats
from .router import (CanaryController, ResultCache, RouterServer,
                     TokenBucket)
from .server import InferenceServer
from .weightstore import WeightStore, WeightStoreError, WeightWatcher

__all__ = ["InferenceEngine", "MicroBatcher", "QueueFull", "Draining",
           "InferenceServer", "ServingClient", "ServingError",
           "ConnectionPool", "RouterServer", "Membership", "Replica",
           "CircuitBreaker", "BreakerState", "TokenBucket", "ResultCache",
           "DecodeEngine", "ContinuousBatcher", "PagedKVCache",
           "OutOfPages", "WeightStore", "WeightWatcher", "WeightStoreError",
           "CanaryController", "policies", "ReplicaView", "VersionStats",
           "Autoscaler", "ReplicaManager", "ExecutableStore"]
