"""Fleet trace collection: tail-sampled assembly of cross-process request
timelines.

A request that traverses the fleet leaves spans in several process-local
:class:`~sparkflow_tpu.obs.spans.Tracer` rings: the router's dispatch and
hedge-attempt spans, each replica's queue/admission/prefill/per-tick decode
spans. This module turns those fragments into ONE waterfall:

- :func:`trace_spans` extracts every span belonging to a trace id from one
  tracer ring — seed spans carry ``trace_id`` in their args; the closure
  adds their descendants (children rarely repeat the id) and ancestors, and
  normalization maps each onto the wall clock via the tracer's origin pair
  and fingerprints its ids, so fragments from different processes merge
  without collisions. Replicas serve this as ``GET /traces/<trace_id>``.
- :class:`TraceCollector` lives in the router. After each request it makes
  a **tail-based** retention decision (:meth:`TraceCollector.should_keep`):
  errored, hedged, retried, or slow-vs-live-p95 requests are always kept;
  a configurable head-sample fraction of the boring rest rides along.
  Kept traces are assembled synchronously — fetch the winning (and losing)
  replicas' fragments, merge with the router's own, link the replica roots
  to the dispatch attempts via the ``parent_uid`` each replica recorded
  from its ``traceparent`` header — and stored in a bounded ring
  (:attr:`TraceCollector.max_traces`, same boundedness contract as
  ``MAX_SPANS``). Because keep-worthy requests are rare by construction,
  the hot path pays only the decision, never the assembly.

Exports: :meth:`TraceCollector.to_chrome_trace` renders a merged trace as
Chrome-trace JSON (one synthetic pid per process fingerprint, so
chrome://tracing / Perfetto shows each process as its own lane on one
timeline); :meth:`TraceCollector.export_jsonl` writes one span per line for
log pipelines.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple
from urllib.parse import urlparse

from ..utils.metrics import Metrics, default_metrics
from .spans import Span, Tracer

__all__ = ["TraceCollector", "trace_spans", "normalize_span"]

#: assembled traces retained by a collector (oldest evicted first)
MAX_TRACES = 256

#: minimum observations before "slow vs live p95" can fire (a cold
#: histogram's p95 is noise; until then only error/hedge/retry/head keep)
MIN_P95_SAMPLES = 20


def normalize_span(tracer: Tracer, s: Span,
                   thread: Optional[str] = None) -> Dict[str, Any]:
    """One span → a process-independent record: fingerprinted ids, wall-clock
    start (``ts``, epoch seconds), duration. A root span that carried its
    cross-process parent in ``args["parent_uid"]`` (stamped by the server
    from the incoming ``traceparent``) gets that uid as its ``parent_id``,
    which is what links a replica's fragment under the router's dispatch
    attempt in the merged waterfall."""
    t1 = s.t1 if s.t1 is not None else s.t0
    rec: Dict[str, Any] = {
        "name": s.name,
        "span_id": tracer.span_uid(s.span_id),
        "parent_id": tracer.span_uid(s.parent_id),
        "process": tracer.fingerprint,
        "ts": tracer.wall_time(s.t0),
        "duration_s": round(t1 - s.t0, 9),
    }
    if thread is not None:
        rec["thread"] = thread
    if s.args:
        rec["args"] = dict(s.args)
        if rec["parent_id"] is None and s.args.get("parent_uid"):
            rec["parent_id"] = s.args["parent_uid"]
    return rec


def trace_spans(tracer: Tracer, trace_id: str) -> List[Dict[str, Any]]:
    """Every span in ``tracer``'s ring belonging to ``trace_id``, as
    normalized records sorted by wall-clock start.

    Seeds are spans whose args carry the trace id; the transitive closure
    adds descendants (a decode tick parents to the request span without
    repeating the id) and ancestors, so callers only need to stamp the id
    on the boundary spans."""
    with tracer._lock:
        spans = list(tracer._spans)
        tids = dict(tracer._tids)
    keep = {s.span_id for s in spans
            if s.args and s.args.get("trace_id") == trace_id}
    if not keep:
        return []
    # descendants: children point at parents, so iterate to a fixpoint
    changed = True
    while changed:
        changed = False
        for s in spans:
            if (s.span_id not in keep and s.parent_id is not None
                    and s.parent_id in keep):
                keep.add(s.span_id)
                changed = True
    # ancestors: walk each seed's parent chain
    by_id = {s.span_id: s for s in spans}
    for sid in list(keep):
        cur = by_id.get(sid)
        while cur is not None and cur.parent_id is not None:
            if cur.parent_id in keep:
                break
            keep.add(cur.parent_id)
            cur = by_id.get(cur.parent_id)
    out = [normalize_span(tracer, s, thread=tids.get(s.tid, str(s.tid)))
           for s in spans if s.span_id in keep]
    out.sort(key=lambda r: r["ts"])
    return out


class TraceCollector:
    """Router-side tail-sampled trace buffer + cross-process assembly.

    ``tracer`` is the router's own tracer (its dispatch/hedge spans seed
    every assembly). Retention knobs:

    - ``head_sample`` — fraction of unremarkable requests kept anyway
      (0 disables; 1.0 keeps everything).
    - ``slow_factor`` — keep when ``duration_ms >= slow_factor × live p95``
      of the ``latency_hist`` histogram (windowed, so "slow" tracks what
      the fleet did recently, not its whole life).
    - errored / hedged / retried requests are always kept — the tail that
      actually needs explaining.

    Assembly fetches ``GET /traces/<trace_id>`` from each replica URL the
    request touched — outside the collector lock, so a slow replica never
    stalls concurrent keep decisions — and merges the fragments with the
    router's own spans into one ``ts``-ordered record ring."""

    def __init__(self, tracer: Tracer, *, metrics: Optional[Metrics] = None,
                 head_sample: float = 0.01, slow_factor: float = 1.0,
                 latency_hist: str = "router/request_ms",
                 max_traces: int = MAX_TRACES, fetch_timeout_s: float = 2.0,
                 seed: Optional[int] = None):
        self.tracer = tracer
        self.metrics = metrics if metrics is not None else default_metrics
        self.head_sample = float(head_sample)
        self.slow_factor = float(slow_factor)
        self.latency_hist = latency_hist
        self.max_traces = int(max_traces)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._seen = 0

    # -- retention -----------------------------------------------------------

    def should_keep(self, duration_ms: float, *, error: bool = False,
                    hedged: bool = False,
                    retried: bool = False) -> Optional[str]:
        """Tail-based retention verdict: the reason string when the trace
        should be kept in full, None when it should be dropped."""
        if error:
            return "error"
        if hedged:
            return "hedged"
        if retried:
            return "retried"
        with self._lock:
            self._seen += 1
            seen = self._seen
            head = self._rng.random() < self.head_sample
        if seen >= MIN_P95_SAMPLES:
            try:
                p95 = self.metrics.percentile(self.latency_hist, 95,
                                              window=1024)
            except (KeyError, ValueError):
                p95 = None
            if p95 is not None and duration_ms >= self.slow_factor * p95:
                return "slow"
        if head:
            return "head"
        return None

    # -- assembly ------------------------------------------------------------

    def observe_request(self, trace_id: str, duration_ms: float, *,
                        error: bool = False, hedged: bool = False,
                        retried: bool = False,
                        replicas: Iterable[str] = ()) -> Optional[Dict[str, Any]]:
        """Per-request hook: decide, and assemble only when kept. Returns
        the assembled trace record or None (dropped)."""
        reason = self.should_keep(duration_ms, error=error, hedged=hedged,
                                  retried=retried)
        if reason is None:
            self.metrics.incr("trace/sampled_out")
            return None
        return self.assemble(trace_id, replicas=replicas, reason=reason,
                             duration_ms=duration_ms)

    def assemble(self, trace_id: str, *, replicas: Iterable[str] = (),
                 reason: str = "manual",
                 duration_ms: Optional[float] = None) -> Dict[str, Any]:
        """Merge the router's own spans for ``trace_id`` with each replica's
        ``GET /traces/<trace_id>`` fragment into one wall-clock-ordered
        trace; store it in the bounded ring and return it."""
        records = trace_spans(self.tracer, trace_id)
        for url in replicas:
            records.extend(self._fetch(url, trace_id))
        # de-duplicate on the fingerprinted uid (a replica probed twice, or
        # a local span that also came back over the wire, merges to one)
        seen: Dict[str, Dict[str, Any]] = {}
        for rec in records:
            seen.setdefault(rec["span_id"], rec)
        spans = sorted(seen.values(), key=lambda r: r["ts"])
        trace = {"trace_id": trace_id, "reason": reason, "spans": spans,
                 "processes": sorted({r["process"] for r in spans}),
                 "replicas": list(replicas)}
        if duration_ms is not None:
            trace["duration_ms"] = duration_ms
        with self._lock:
            self._traces[trace_id] = trace
            self._traces.move_to_end(trace_id)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
        self.metrics.incr("trace/kept")
        return trace

    def _fetch(self, url: str, trace_id: str) -> List[Dict[str, Any]]:
        """One replica's fragment via a one-shot GET (no pooling: assembly
        is rare by construction, and a dedicated connection keeps this path
        independent of the dispatch pools). Any failure returns [] — a
        replica that died mid-request still yields a partial trace."""
        parsed = urlparse(url if "//" in url else f"http://{url}")
        conn = http.client.HTTPConnection(parsed.hostname,
                                          parsed.port or 80,
                                          timeout=self.fetch_timeout_s)
        try:
            conn.request("GET", f"/traces/{trace_id}")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return []
            obj = json.loads(body.decode("utf-8"))
            spans = obj.get("spans", [])
            return [s for s in spans if isinstance(s, dict)]
        except (OSError, ValueError):
            self.metrics.incr("trace/fetch_errors")
            return []
        finally:
            conn.close()

    # -- introspection -------------------------------------------------------

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._traces.get(trace_id)

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._traces.values())

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    # -- export --------------------------------------------------------------

    def to_chrome_trace(self, trace_id: str) -> Dict[str, Any]:
        """One assembled trace as Chrome-trace JSON: a synthetic pid per
        process fingerprint (each process gets its own lane), ts/dur in
        microseconds relative to the trace's first span. Raises KeyError
        for an unknown trace id."""
        trace = self.get(trace_id)
        if trace is None:
            raise KeyError(f"no assembled trace {trace_id!r}")
        spans = trace["spans"]
        t0 = min((r["ts"] for r in spans), default=0.0)
        pids = {proc: i + 1 for i, proc in enumerate(trace["processes"])}
        events: List[Dict[str, Any]] = []
        for proc, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": f"process {proc}"}})
        threads: Dict[Tuple[int, str], int] = {}
        for rec in spans:
            pid = pids[rec["process"]]
            key = (pid, rec.get("thread", "main"))
            tid = threads.get(key)
            if tid is None:
                tid = threads[key] = len([k for k in threads
                                          if k[0] == pid]) + 1
                events.append({"ph": "M", "name": "thread_name", "pid": pid,
                               "tid": tid, "args": {"name": key[1]}})
            args = dict(rec.get("args") or {})
            args["span_id"] = rec["span_id"]
            if rec.get("parent_id"):
                args["parent_id"] = rec["parent_id"]
            args["trace_id"] = trace_id
            events.append({
                "name": rec["name"], "ph": "X", "cat": "trace",
                "ts": round((rec["ts"] - t0) * 1e6, 3),
                "dur": round(rec["duration_s"] * 1e6, 3),
                "pid": pid, "tid": tid, "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, trace_id: str, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(trace_id), f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, trace_id: str, path: str) -> str:
        """One span record per line (already wall-clock ``ts``-ordered)."""
        trace = self.get(trace_id)
        if trace is None:
            raise KeyError(f"no assembled trace {trace_id!r}")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            for rec in trace["spans"]:
                f.write(json.dumps(dict(rec, trace_id=trace_id)) + "\n")
        os.replace(tmp, path)
        return path

    @staticmethod
    def waterfall(trace: Dict[str, Any]) -> str:
        """Human-readable indentation waterfall of an assembled trace —
        what ``examples/trace_smoke.py`` prints."""
        spans = trace["spans"]
        children: Dict[Optional[str], List[Dict[str, Any]]] = {}
        ids = {r["span_id"] for r in spans}
        for rec in spans:
            parent = rec.get("parent_id")
            if parent not in ids:
                parent = None  # orphaned fragment → render at the root
            children.setdefault(parent, []).append(rec)
        t0 = min((r["ts"] for r in spans), default=0.0)
        lines = [f"trace {trace['trace_id']} "
                 f"(reason={trace.get('reason')}, "
                 f"processes={len(trace.get('processes', []))})"]

        def walk(parent: Optional[str], depth: int) -> None:
            for rec in sorted(children.get(parent, ()),
                              key=lambda r: r["ts"]):
                label = ""
                args = rec.get("args") or {}
                if "outcome" in args:
                    label = f" [{args['outcome']}]"
                lines.append(
                    f"  {'  ' * depth}+{(rec['ts'] - t0) * 1e3:9.3f}ms "
                    f"{rec['duration_s'] * 1e3:9.3f}ms  {rec['name']}"
                    f"{label}  ({rec['process'][-6:]})")
                walk(rec["span_id"], depth + 1)

        walk(None, 0)
        return "\n".join(lines)
