"""Unit tests for the graph DSL, spec serialization, and JAX executor."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.graphdef import GraphDef, GraphModel, list_to_params, params_to_list


def mlp_graph():
    x = nn.placeholder([None, 20], name="x")
    y = nn.placeholder([None, 3], name="y")
    h = nn.dense(x, 32, activation="relu")
    out = nn.dense(h, 3, name="out")
    nn.argmax(out, 1, name="pred")
    nn.softmax_cross_entropy(y, out)


def test_build_graph_returns_json():
    mg = build_graph(mlp_graph)
    assert isinstance(mg, str)
    g = GraphDef.from_json(mg)
    assert g.to_json() == GraphDef.from_json(g.to_json()).to_json()


def test_tensor_name_compat():
    """TF1-style tensor names resolve: bare, ':0', and scope-qualified."""

    def m():
        x = nn.placeholder([None, 4], name="x")
        nn.dense(x, 2, activation="sigmoid", name="out")

    g = GraphDef.from_json(build_graph(m))
    a = g.resolve("out/Sigmoid:0")
    b = g.resolve("out:0")
    c = g.resolve("out")
    assert a == b == c
    with pytest.raises(KeyError):
        g.resolve("missing:0")


def test_apply_and_shapes():
    m = GraphModel.from_json(build_graph(mlp_graph))
    params = m.init(jax.random.PRNGKey(0))
    x = np.random.randn(8, 20).astype(np.float32)
    y = np.eye(3)[np.random.randint(0, 3, 8)].astype(np.float32)
    outs = m.apply(params, {"x:0": x, "y:0": y}, ["out:0", "pred:0"])
    assert outs["out:0"].shape == (8, 3)
    assert outs["pred:0"].shape == (8,)
    lv = m.loss_vector(params, {"x": x, "y": y})
    assert lv.shape == (8,)
    assert np.all(np.isfinite(np.asarray(lv)))


def test_grad_flows():
    m = GraphModel.from_json(build_graph(mlp_graph))
    params = m.init(jax.random.PRNGKey(0))
    x = np.random.randn(4, 20).astype(np.float32)
    y = np.eye(3)[np.random.randint(0, 3, 4)].astype(np.float32)
    g = jax.grad(lambda p: m.loss_vector(p, {"x": x, "y": y}).mean())(params)
    norms = [float(jnp.linalg.norm(leaf)) for leaf in jax.tree.leaves(g)]
    assert any(n > 0 for n in norms)


def test_weight_list_order_stable_after_tree_ops():
    """jax.tree ops rebuild dicts sorted; flat weight order must not change."""
    m = GraphModel.from_json(build_graph(mlp_graph))
    params = m.init(jax.random.PRNGKey(0))
    shuffled = jax.tree.map(lambda a: a + 1.0, params)  # rebuilds dicts sorted
    wl = params_to_list(m, shuffled)
    back = list_to_params(m, wl)
    for lname in shuffled:
        for pname in shuffled[lname]:
            np.testing.assert_allclose(np.asarray(shuffled[lname][pname]),
                                       np.asarray(back[lname][pname]))


def test_cnn_shapes():
    def cnn():
        x = nn.placeholder([None, 784], name="x")
        y = nn.placeholder([None, 10], name="y")
        xr = nn.reshape(x, [-1, 28, 28, 1])
        c1 = nn.conv2d(xr, 8, 5, activation="relu")
        p1 = nn.max_pooling2d(c1, 2, 2)
        c2 = nn.conv2d(p1, 16, 3, activation="relu")
        p2 = nn.max_pooling2d(c2, 2, 2)
        out = nn.dense(nn.flatten(p2), 10, name="out")
        nn.softmax_cross_entropy(y, out)

    m = GraphModel.from_json(build_graph(cnn))
    assert m.tensor_shape("out:0") == (None, 10)
    params = m.init(jax.random.PRNGKey(0))
    x = np.random.rand(2, 784).astype(np.float32)
    out = m.apply(params, {"x": x}, ["out:0"])["out:0"]
    assert out.shape == (2, 10)


def test_unsupervised_autoencoder_graph():
    def ae():
        x = nn.placeholder([None, 12], name="x")
        h = nn.dense(x, 4, activation="sigmoid", name="bottleneck")
        o = nn.dense(h, 12, activation="sigmoid")
        nn.mean_squared_error(o, x)

    m = GraphModel.from_json(build_graph(ae))
    params = m.init(jax.random.PRNGKey(1))
    x = np.random.rand(5, 12).astype(np.float32)
    mid = m.apply(params, {"x": x}, ["bottleneck/Sigmoid:0"])["bottleneck/Sigmoid:0"]
    assert mid.shape == (5, 4)
    assert m.loss_vector(params, {"x": x}).shape == (5,)


def test_dropout_train_vs_eval():
    def m():
        x = nn.placeholder([None, 100], name="x")
        kp = nn.placeholder_with_default(0.5, name="kp")
        h = nn.dropout(x, keep_prob=kp)
        nn.mean_squared_error(h, x)

    gm = GraphModel.from_json(build_graph(m))
    params = gm.init(jax.random.PRNGKey(0))
    x = np.ones((4, 100), np.float32)
    # eval mode: identity
    out = gm.apply(params, {"x": x}, ["dropout:0"], train=False)["dropout:0"]
    np.testing.assert_allclose(np.asarray(out), x)
    # train mode, default keep=0.5: roughly half dropped, survivors scaled 2x
    out_t = gm.apply(params, {"x": x}, ["dropout:0"], train=True,
                     rng=jax.random.PRNGKey(3))["dropout:0"]
    frac_zero = float((np.asarray(out_t) == 0).mean())
    assert 0.3 < frac_zero < 0.7
    # train mode but keep fed as 1.0 (predict-style feed): identity
    out_k = gm.apply(params, {"x": x, "kp": 1.0}, ["dropout:0"], train=True,
                     rng=jax.random.PRNGKey(3))["dropout:0"]
    np.testing.assert_allclose(np.asarray(out_k), x)


def test_reshape_double_unknown():
    def m():
        x = nn.placeholder([None, 12], name="x")
        r = nn.reshape(x, [-1, 3, -1])
        nn.mean_squared_error(r, r)

    gm = GraphModel.from_json(build_graph(m))
    params = gm.init(jax.random.PRNGKey(0))
    out = gm.apply(params, {"x": np.zeros((2, 12), np.float32)}, ["reshape:0"])
    assert out["reshape:0"].shape == (2, 3, 4)


def test_extend_deserialized_graph_no_alias_clobber():
    def m():
        x = nn.placeholder([None, 4], name="x")
        nn.dense(x, 2)  # auto-named 'dense'

    g = GraphDef.from_json(build_graph(m))
    before = g.resolve("dense:0")
    with nn.graph_scope(g):
        nn.dense(nn.Sym(g, 0), 3)  # must become dense_1, not clobber 'dense:0'
    assert g.resolve("dense:0") == before
    assert "dense_1:0" in g.aliases
