"""MNIST MLP via Pipeline.fit — translation of the reference's
``examples/simple_dnn.py`` to the TPU-native framework.

The model function ports line-for-line from TF1 to :mod:`sparkflow_tpu.nn`;
the Estimator params are identical. With pyspark installed this uses the real
SparkSession; standalone it runs on localml. MNIST csv is loaded if present
(same path the reference expects), else a synthetic stand-in is generated so
the example always runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

from sparkflow_tpu import nn
from sparkflow_tpu.graph_utils import build_adam_config, build_graph
from sparkflow_tpu.tensorflow_async import SparkAsyncDL
from sparkflow_tpu.pipeline_util import PysparkPipelineWrapper
from sparkflow_tpu.compat import USING_PYSPARK

if USING_PYSPARK:
    from pyspark.sql import SparkSession
    from pyspark.ml.feature import VectorAssembler, OneHotEncoder
    from pyspark.ml.evaluation import MulticlassClassificationEvaluator
    from pyspark.ml.pipeline import Pipeline, PipelineModel
    from pyspark.sql.functions import rand
else:
    from sparkflow_tpu.localml import (LocalSession as SparkSession,
                                       VectorAssembler, OneHotEncoder,
                                       MulticlassClassificationEvaluator,
                                       Pipeline, PipelineModel)
    from sparkflow_tpu.localml.sql import functions
    rand = functions.rand


def small_model():
    x = nn.placeholder([None, 784], name='x')
    y = nn.placeholder([None, 10], name='y')
    layer1 = nn.dense(x, 256, activation='relu', kernel_initializer='glorot_uniform')
    layer2 = nn.dense(layer1, 256, activation='relu', kernel_initializer='glorot_uniform')
    out = nn.dense(layer2, 10, kernel_initializer='glorot_uniform')
    z = nn.argmax(out, 1, name='out')
    loss = nn.softmax_cross_entropy(y, out)
    return loss


def load_df(spark, n_synth=4096):
    if os.environ.get("SPARKFLOW_TPU_SMOKE"):  # fast CI/smoke path
        n_synth = 512
    path = os.path.join(os.path.dirname(__file__), 'mnist_train.csv')
    if os.path.exists(path):
        return spark.read.option("inferSchema", "true").csv(path).orderBy(rand())
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(n_synth):
        label = rs.randint(0, 10)
        px = rs.rand(784) * (0.3 + 0.07 * label)
        rows.append(tuple([int(label)] + px.tolist()))
    cols = [f"_c{i}" for i in range(785)]
    return spark.createDataFrame(rows, cols).orderBy(rand())


if __name__ == '__main__':
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    spark = SparkSession.builder \
        .appName("examples") \
        .master('local[4]').config('spark.driver.memory', '2g') \
        .getOrCreate()

    df = load_df(spark)
    mg = build_graph(small_model)
    adam_config = build_adam_config(learning_rate=0.001, beta1=0.9, beta2=0.999)

    vector_assembler = VectorAssembler(inputCols=df.columns[1:785], outputCol='features')
    encoder = OneHotEncoder(inputCol='_c0', outputCol='labels', dropLast=False)

    spark_model = SparkAsyncDL(
        inputCol='features',
        tensorflowGraph=mg,
        tfInput='x:0',
        tfLabel='y:0',
        tfOutput='out:0',
        tfOptimizer='adam',
        miniBatchSize=300,
        miniStochasticIters=1,
        shufflePerIter=True,
        iters=50,
        predictionCol='predicted',
        labelCol='labels',
        partitions=4,
        verbose=1,
        optimizerOptions=adam_config
    )

    p = Pipeline(stages=[vector_assembler, encoder, spark_model]).fit(df)
    p.write().overwrite().save('simple_dnn')

    loaded_pipeline = PysparkPipelineWrapper.unwrap(PipelineModel.load('simple_dnn'))

    predictions = loaded_pipeline.transform(df)
    evaluator = MulticlassClassificationEvaluator(
        labelCol="_c0", predictionCol="predicted", metricName="accuracy")
    accuracy = evaluator.evaluate(predictions)
    print("Test Error = %g" % (1.0 - accuracy))
