"""Mixture-of-Experts transformer with expert parallelism over ``ep``.

Switch-style top-1 routing with capacity-based token dispatch and a
load-balancing auxiliary loss (Fedus et al., Switch Transformer; retrieved
PAPERS.md pattern). Each token is routed to exactly one expert; every expert
owns a fixed-size buffer of ``capacity = ceil(capacity_factor * tokens / E)``
slots, so expert FLOPs scale with *tokens*, not ``tokens x E`` — tokens beyond
an expert's capacity are dropped (their FFN contribution is zero, the residual
stream still carries them), exactly the Switch semantics. Dispatch and combine
are gathers over a statically-shaped slot table, which keeps everything
jit-compatible (no ragged shapes) and lets GSPMD shard the expert einsums over
the ``ep`` mesh axis (``param_pspecs``: the expert bank's leading axis lives on
``ep``).

The auxiliary loss is threaded *functionally* through the block stack (no
mutable instance state), so concurrent traces of one model instance are safe.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .base import _Names
from .registry import register_model
from .transformer import TransformerLM, _layer_norm


class _MoEMixin:
    """Replaces the dense FFN with a capacity-routed expert bank on MoE layers."""

    def _init_moe(self, num_experts: int, moe_every: int, aux_weight: float,
                  capacity_factor: float = 1.25, router_top_k: int = 1,
                  ep_axis: Optional[str] = None):
        self.num_experts = num_experts
        self.moe_every = max(1, moe_every)
        self.aux_weight = aux_weight
        self.capacity_factor = capacity_factor
        self.router_top_k = max(1, min(router_top_k, num_experts))
        # ep_axis: run the FFN via all_to_all dispatch inside shard_map over
        # this mesh axis (ops/moe_dispatch; top-k like the GSPMD form) — the
        # communicating form of expert parallelism; None keeps the GSPMD
        # slot dispatch
        self.ep_axis = ep_axis

    def _is_moe_layer(self, i: int) -> bool:
        return (i % self.moe_every) == (self.moe_every - 1)

    def _capacity(self, n_tokens: int) -> int:
        return max(1, int(math.ceil(self.capacity_factor * n_tokens
                                    / self.num_experts)))

    def _moe_block_specs(self):
        h, m, e = self.hidden, self.mlp_dim, self.num_experts
        specs = super()._block_specs()
        for k in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias"):
            del specs[k]
        specs.update({
            "router": ((h, e), "normal(0.02)"),
            "experts_fc1": ((e, h, m), "normal(0.02)"),
            "experts_b1": ((e, m), "zeros"),
            "experts_fc2": ((e, m, h), "normal(0.02)"),
            "experts_b2": ((e, h), "zeros"),
        })
        return specs

    def _moe_block_pspecs(self):
        specs = super()._block_pspecs()
        for k in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias"):
            del specs[k]
        specs.update({
            "router": P(),
            "experts_fc1": P("ep", None, None),
            "experts_b1": P("ep", None),
            "experts_fc2": P("ep", None, None),
            "experts_b2": P("ep", None),
        })
        return specs

    def param_specs(self):
        specs = super().param_specs()
        for i in range(self.num_layers):
            if self._is_moe_layer(i):
                specs[f"block_{i}"] = self._moe_block_specs()
        return specs

    def param_pspecs(self):
        specs = super().param_pspecs()
        for i in range(self.num_layers):
            if self._is_moe_layer(i):
                specs[f"block_{i}"] = self._moe_block_pspecs()
        return specs

    def _moe_mlp(self, bp, x, token_mask=None):
        """x [B,S,H] -> (routed expert FFN output [B,S,H], aux loss scalar).

        Capacity-routed top-1 dispatch: each token claims the next free slot
        in its expert's [C,H] buffer via a cumulative-count position; the slot
        table is a static-shape scatter/gather, so per-token work is O(C*H*M)
        per expert regardless of E. Slot buffers carry an extra "overflow" row
        that dropped tokens read back as zeros. ``token_mask`` [B,S] excludes
        padding tokens: they claim no capacity (identical all-zero pad rows
        would otherwise flood one expert and evict real tokens) and don't
        enter the load-balancing statistics.
        """
        if self.ep_axis is not None:
            from ..ops.moe_dispatch import all_to_all_moe_ffn
            return all_to_all_moe_ffn(
                x, bp["router"], bp["experts_fc1"], bp["experts_b1"],
                bp["experts_fc2"], bp["experts_b2"], self.ep_axis,
                self.num_experts, self.capacity_factor, token_mask,
                top_k=self.router_top_k)
        return self._moe_mlp_slots(bp, x, token_mask)

    def _moe_mlp_slots(self, bp, x, token_mask=None, ep_axis=None):
        """Slot-table dispatch body of :meth:`_moe_mlp`. With ``ep_axis``
        (decode-plane expert parallelism inside a ``shard_map``, batch
        *replicated* — unlike ``all_to_all_moe_ffn``'s batch-sharded form):
        every shard computes the identical global routing from the replicated
        router, then dispatches only the tokens routed to its *local* expert
        bank (``bp['experts_*']`` leading dim is ``E/ep``); each token's FFN
        output lives on exactly one shard and the final ``psum`` rejoins the
        replicated stream by summing one real row with exact zeros —
        bit-identical to the unsharded dispatch."""
        b, s, h = x.shape
        e = self.num_experts
        k = self.router_top_k
        n = b * s
        c = self._capacity(n * k)
        xf = x.reshape(n, h)
        if ep_axis is None:
            e_loc, lo = e, 0
        else:
            e_loc = bp["experts_fc1"].shape[0]             # local bank E/ep
            lo = jax.lax.axis_index(ep_axis) * e_loc

        router_logits = jnp.einsum("nh,he->ne", xf.astype(jnp.float32),
                                   bp["router"])
        probs = jax.nn.softmax(router_logits, axis=-1)           # [N,E]
        top_vals, top_idx = jax.lax.top_k(probs, k)              # [N,k]
        if k == 1:
            gates = top_vals  # Switch semantics: gate = max prob
        else:
            # GShard top-k: gates renormalized over the chosen experts
            gates = top_vals / jnp.maximum(
                jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)
        if token_mask is not None:
            live = token_mask.reshape(n).astype(jnp.float32)
        else:
            live = None

        onehots = [jax.nn.one_hot(top_idx[:, ci], e, dtype=jnp.float32)
                   for ci in range(k)]
        if live is not None:
            onehots = [oh * live[:, None] for oh in onehots]

        # Switch load-balancing loss over live tokens (first-choice fractions)
        denom = jnp.sum(live) if live is not None else float(n)
        denom = jnp.maximum(denom, 1.0)
        probs_live = probs * live[:, None] if live is not None else probs
        aux = e * jnp.sum((jnp.sum(onehots[0], axis=0) / denom)
                          * (jnp.sum(probs_live, axis=0) / denom))

        # buffer positions: ALL first choices claim capacity before any
        # second choice (GShard priority), via cumsum over the stacked
        # [k*N, E] assignment matrix in choice-major order
        stacked = jnp.concatenate(onehots, axis=0)               # [k*N, E]
        pos_all = jnp.cumsum(stacked, axis=0) - 1.0              # [k*N, E]
        xf_pad = jnp.concatenate([xf, jnp.zeros((1, h), xf.dtype)], axis=0)
        token_for_slot = jnp.full((e_loc * c + 1,), n, dtype=jnp.int32)
        slots = []
        for ci in range(k):
            oh = onehots[ci]
            pos = jnp.sum(pos_all[ci * n:(ci + 1) * n] * oh,
                          axis=-1).astype(jnp.int32)             # [N]
            # slot positions come from the GLOBAL cumsum: capacity drops are
            # decided identically on every shard, ownership only selects
            # which shard serves the surviving (expert, slot) claims
            loc = top_idx[:, ci].astype(jnp.int32) - lo
            kept = ((pos < c) & (jnp.sum(oh, axis=-1) > 0)
                    & (loc >= 0) & (loc < e_loc))
            slot = jnp.where(kept, loc * c + pos, e_loc * c)
            token_for_slot = token_for_slot.at[slot].set(
                jnp.arange(n, dtype=jnp.int32))
            slots.append(slot)
        xe = xf_pad[token_for_slot[:e_loc * c]].reshape(e_loc, c, h)  # [E,C,H]

        # expert FFN over the slot buffers; leading axis sharded over 'ep'
        hmid = jnp.einsum("ech,ehm->ecm", xe, bp["experts_fc1"].astype(xe.dtype))
        hmid = jax.nn.gelu(hmid + bp["experts_b1"].astype(hmid.dtype)[:, None, :])
        out = jnp.einsum("ecm,emh->ech", hmid, bp["experts_fc2"].astype(hmid.dtype))
        out = out + bp["experts_b2"].astype(out.dtype)[:, None, :]

        # combine: each token reads its k slots back, weighted by its gates;
        # overflow slot row is zero (dropped AND non-local choices contribute
        # nothing — under ep the psum supplies the owning shard's row)
        out_pad = jnp.concatenate([out.reshape(e_loc * c, h),
                                   jnp.zeros((1, h), out.dtype)], axis=0)
        y = sum(out_pad[slots[ci]] * gates[:, ci:ci + 1].astype(out.dtype)
                for ci in range(k))
        if ep_axis is not None:
            y = jax.lax.psum(y, ep_axis)
        return y.reshape(b, s, h).astype(x.dtype), aux

    def _block_aux(self, bp, x, mask, causal, train, rng):
        """Base ``_block_aux`` for dense blocks; routed FFN + router aux on
        MoE blocks (the encoder loop lives in ``_TransformerBase._encode``)."""
        if "router" not in bp:
            return super()._block_aux(bp, x, mask, causal, train, rng)
        b, s, h = x.shape
        y = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
        qkv = self._proj(bp, "qkv_", y)
        qkv = qkv.reshape(b, s, 3, self.num_heads, self.head_dim)
        q, k, v = [jnp.transpose(qkv[:, :, i], (0, 2, 1, 3)) for i in range(3)]
        att = self._attention(q, k, v, mask, causal)
        att = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, s, h)
        att, rng = self._dropout(self._proj(bp, "o_", att), train, rng)
        x = x + att
        y = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
        y, aux = self._moe_mlp(bp, y, token_mask=mask)
        y, rng = self._dropout(y, train, rng)
        return x + y, rng, aux

    # -- decode plane ---------------------------------------------------------
    #
    # The serving engine drives the same prefill/decode/verify entry points a
    # dense TransformerLM exposes; MoE blocks override the three block-step
    # forms to swap the dense FFN for the routed expert bank. The router aux
    # loss is a training quantity — decode discards it. ``ep_axis`` selects
    # the replicated-batch local-bank dispatch (``_moe_mlp_slots``), NOT the
    # batch-sharded ``all_to_all_moe_ffn`` the training path uses.

    def _block(self, bp, x, mask, causal, train, rng, with_kv: bool = False,
               tp_axis=None, ep_axis=None):
        if "router" not in bp:
            return super()._block(bp, x, mask, causal, train, rng,
                                  with_kv=with_kv, tp_axis=tp_axis,
                                  ep_axis=ep_axis)
        b, s, h = x.shape
        y = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
        qkv = self._proj(bp, "qkv_", y)
        heads = qkv.shape[-1] // (3 * self.head_dim)
        qkv = qkv.reshape(b, s, 3, heads, self.head_dim)
        qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))
        q, k, v = qkv[0], qkv[1], qkv[2]
        att = self._attention(q, k, v, mask, causal)
        att = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, s, -1)
        att, rng = self._dropout(self._proj(bp, "o_", att), train, rng)
        if tp_axis is not None:
            att = jax.lax.psum(att, tp_axis)
        x = x + att
        y = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
        y, _ = self._moe_mlp_slots(bp, y, token_mask=mask, ep_axis=ep_axis)
        y, rng = self._dropout(y, train, rng)
        if with_kv:
            return x + y, rng, k, v
        return x + y, rng

    def _block_decode(self, bp, x, layer, cache, pos, attend,
                      tp_axis=None, ep_axis=None):
        if "router" not in bp:
            return super()._block_decode(bp, x, layer, cache, pos, attend,
                                         tp_axis=tp_axis, ep_axis=ep_axis)
        b, _, h = x.shape
        y = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
        qkv = self._proj(bp, "qkv_", y)
        heads = qkv.shape[-1] // (3 * self.head_dim)
        qkv = qkv.reshape(b, 3, heads, self.head_dim)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        att, cache = attend(layer, q, k, v, cache, pos)
        att = self._proj(bp, "o_", att.reshape(b, 1, -1))
        if tp_axis is not None:
            att = jax.lax.psum(att, tp_axis)
        x = x + att
        y = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
        y, _ = self._moe_mlp_slots(bp, y, ep_axis=ep_axis)
        return x + y, cache

    def _block_suffix(self, bp, x, layer, cache, start, attend,
                      tp_axis=None, ep_axis=None):
        if "router" not in bp:
            return super()._block_suffix(bp, x, layer, cache, start, attend,
                                         tp_axis=tp_axis, ep_axis=ep_axis)
        b, s, h = x.shape
        y = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
        qkv = self._proj(bp, "qkv_", y)
        heads = qkv.shape[-1] // (3 * self.head_dim)
        qkv = qkv.reshape(b, s, 3, heads, self.head_dim)
        qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))
        q, k, v = qkv[0], qkv[1], qkv[2]
        att, cache = attend(layer, q, k, v, cache, start)
        att = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, s, -1)
        att = self._proj(bp, "o_", att)
        if tp_axis is not None:
            att = jax.lax.psum(att, tp_axis)
        x = x + att
        y = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
        y, _ = self._moe_mlp_slots(bp, y, ep_axis=ep_axis)
        return x + y, cache


@register_model("transformer_moe_lm")
class MoETransformerLM(_MoEMixin, TransformerLM):
    """Causal MoE LM: Switch FFN every ``moe_every``-th block, EP shardable.

    Deriving from :class:`TransformerLM` brings the full autoregressive
    decode surface (``prefill``/``decode_step``/``decode_verify``/
    ``prefill_suffix``) — the mixin's block overrides route MoE layers
    through the expert bank, so the serving engine drives an MoE model
    exactly like a dense one (expert-parallel over ``ep`` when configured)."""

    def __init__(self, vocab_size: int, num_experts: int = 8, moe_every: int = 2,
                 router_aux_weight: float = 0.01,
                 capacity_factor: float = 1.25, router_top_k: int = 1,
                 ep_axis: Optional[str] = None, **kw):
        self._init_moe(num_experts, moe_every, router_aux_weight,
                       capacity_factor, router_top_k, ep_axis)
        super().__init__(vocab_size, **kw)
        self.TENSORS = ("input_ids", "attention_mask", "logits", "pred")
        self.graphdef = _Names(self.TENSORS)

    def _logits_aux(self, params, feeds, train, rng):
        """Shared encode + tied-embedding projection for forward and loss."""
        x, _, aux = self._encode(params, feeds, causal=True, train=train,
                                 rng=rng)
        logits = jnp.matmul(x.astype(jnp.float32),
                            params["embed"]["tok"].T.astype(jnp.float32))
        return logits, aux

    def _forward(self, params, feeds, train, rng):
        logits, _ = self._logits_aux(params, feeds, train, rng)
        return {"logits": logits,
                "pred": jnp.argmax(logits, axis=-1).astype(jnp.float32)}

    def _loss(self, params, feeds, train, rng):
        ids = feeds["input_ids"].astype(jnp.int32)
        logits, aux = self._logits_aux(params, feeds, train, rng)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = ids[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        if "attention_mask" in feeds and feeds["attention_mask"] is not None:
            w = feeds["attention_mask"][:, 1:].astype(jnp.float32)
            per = jnp.sum(nll * w, axis=-1) / jnp.maximum(jnp.sum(w, axis=-1), 1e-6)
        else:
            per = jnp.mean(nll, axis=-1)
        # aux spread per-example so the masked-mean trainer stays correct
        return per + aux * self.aux_weight
