"""Synchronous TPU trainer — the replacement for the Hogwild parameter server.

The reference's training runtime (``sparkflow/HogwildSparkModel.py``) spawns a
Flask HTTP parameter server on the driver and has every Spark partition run
``iters`` epochs over partition-local data, exchanging full pickled weight/gradient
payloads per mini-batch. Here the same user-facing knobs (``iters``,
``miniBatchSize``, ``miniStochasticIters``, ``shufflePerIter``,
``partitionShuffles``, ``verbose``, ``loss_callback``) drive a synchronous
data-parallel trainer: the union of partition data is staged onto the device mesh
once, and each epoch is a single XLA-compiled program (shuffle + ``lax.scan`` over
fixed-shape mini-batches) with gradient all-reduce over ICI.

Semantics mapping (documented intentional drift from async Hogwild — the north
star mandates synchronous all-reduce):

- ``iters``             -> epochs over the global dataset (reference: epochs over
                           each partition's local shard, concurrent+async).
- ``miniBatchSize``     -> the global batch size per synchronous step.
- ``miniStochasticIters``-> stochastic mini-batch steps per epoch (drawn from a
                           fresh permutation, i.e. without replacement — matching
                           ``np.random.choice(..., replace=False)`` in
                           ``sparkflow/ml_util.py:121-127``).
- ``partitionShuffles`` -> outer repeats of the whole ``iters`` loop (the
                           reference reshuffles partitions between rounds,
                           ``HogwildSparkModel.py:258-266``; here data is
                           re-permuted on device every epoch anyway).
- ``acquireLock``       -> accepted, no-op: synchronous updates are already
                           serialized; there is no shared mutable server state.
- Convergence semantics therefore differ from lock-free Hogwild by design;
  the update rule equals the reference's ``acquireLock=True`` path with
  simultaneous gradient arrival (sum/mean of worker gradients).
"""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .core import (make_epoch_fn, make_loss_fn, make_multi_epoch_fn,
                   make_predict_fn, pad_to_batches)
from .graphdef import GraphDef, GraphModel, params_to_list
from .optimizers import build_optimizer
from .sharding import ShardingConfig, as_sharding_config

logger = logging.getLogger("sparkflow_tpu")



def _ckpt_state(params, opt_state, step, rng, *, rng_impl):
    """The checkpoint payload schema — single source of truth for every
    save/restore site in fit and fit_stream. Typed PRNG keys (rng_impl set)
    checkpoint as their raw key data; _restore_rng re-wraps them. The impl
    NAME rides along as an ASCII uint8 array (orbax/npz-safe) so restore can
    compare it exactly — 'rbg' and 'unsafe_rbg' have identical key-data
    widths, so width alone cannot tell them apart."""
    import jax.dtypes
    if hasattr(rng, "dtype") and jax.dtypes.issubdtype(rng.dtype,
                                                       jax.dtypes.prng_key):
        rng = jax.random.key_data(rng)
    impl = np.frombuffer((rng_impl or "threefry").encode(), dtype=np.uint8)
    return {"params": params, "opt_state": opt_state,
            "epoch": np.int64(step), "rng": np.asarray(rng),
            "rng_impl": impl.copy()}


class TrainResult:
    """Outcome of a fit: final params + per-epoch mean losses.

    ``stop_reason`` says how the fit ended — ``'completed'`` (ran every
    planned epoch), ``'preempted'`` (SIGTERM checkpoint-and-return; resuming
    on the same checkpoint_dir finishes the run), or ``'nan'``
    (halt_on_nan tripped). ``resilience.run_resilient_fit`` keys its restart
    decision off this field.
    """

    __slots__ = ("params", "losses", "examples_per_sec", "wall_time_s",
                 "stop_reason")

    def __init__(self, params, losses, examples_per_sec, wall_time_s,
                 stop_reason: str = "completed"):
        self.params = params
        self.losses = losses
        self.examples_per_sec = examples_per_sec
        self.wall_time_s = wall_time_s
        self.stop_reason = stop_reason

    @property
    def completed(self) -> bool:
        return self.stop_reason == "completed"


class Trainer:
    """Single-controller synchronous trainer over an optional device mesh.

    Parameters mirror the reference estimator's training Params
    (``sparkflow/tensorflow_async.py:104-121``); ``mesh`` is the TPU-native
    addition — a ``jax.sharding.Mesh`` whose ``'dp'`` axis shards the batch.
    """

    def __init__(self,
                 graph: Union[str, GraphDef, GraphModel],
                 input_name: str,
                 label_name: Optional[str] = None,
                 optimizer: Union[str, optax.GradientTransformation] = "adam",
                 learning_rate: float = 0.01,
                 optimizer_options: Optional[Dict[str, Any]] = None,
                 iters: int = 1000,
                 mini_batch_size: int = 128,
                 mini_stochastic_iters: int = -1,
                 shuffle_per_iter: bool = True,
                 partition_shuffles: int = 1,
                 verbose: int = 0,
                 loss_callback: Optional[Callable] = None,
                 dropout_name: Optional[str] = None,
                 acquire_lock: bool = False,  # accepted for API parity; no-op
                 mesh=None,
                 seed: int = 0,
                 compute_dtype=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0,
                 publish_to=None,
                 publish_every: int = 0,
                 resume_retries: int = 2,
                 straggler_factor: Optional[float] = None,
                 straggler_callback: Optional[Callable] = None,
                 metrics=None,
                 param_sharding: Union[str, None, dict] = "auto",
                 rng_impl: Optional[str] = None,
                 halt_on_nan: bool = False,
                 pp_microbatches: Optional[int] = None,
                 pp_schedule: str = "gpipe",
                 weight_update_sharding: str = "auto",
                 debug_recompiles: bool = False,
                 strategy: Optional[str] = None,
                 elastic: Optional[Dict[str, Any]] = None,
                 sharding: Union[ShardingConfig, dict, None] = None):
        if isinstance(graph, GraphDef):
            self.model = GraphModel(graph, compute_dtype)
        elif isinstance(graph, str):
            from .models import model_from_json
            self.model = model_from_json(graph, compute_dtype)
        else:  # an executable model object (GraphModel or registry model)
            self.model = graph
        # fail fast on bad tensor names (otherwise they surface later as a
        # confusing "placeholder not fed" error from the executor).
        # input_name may be a sequence of tensor names (multi-input models,
        # e.g. input_ids + attention_mask) — features then travel as a tuple.
        for name in (input_name if isinstance(input_name, (list, tuple))
                     else [input_name]):
            self.model.graphdef.resolve(name)
        if label_name:
            self.model.graphdef.resolve(label_name)
        if dropout_name:
            self.model.graphdef.resolve(dropout_name)
        self.input_name = input_name
        self.label_name = label_name
        if isinstance(optimizer, str):
            self.optimizer = build_optimizer(optimizer, learning_rate, optimizer_options)
            self._opt_cfg = dict(optimizer_options or {})
        else:
            self.optimizer = optimizer
            # optax object: optimizer_options (when the caller passes it
            # alongside, as the estimator does) still informs the zero1
            # 'auto' gate; otherwise the object is opaque
            self._opt_cfg = (dict(optimizer_options) if optimizer_options
                             else None)
        self.iters = iters
        self.mini_batch_size = mini_batch_size
        self.mini_stochastic_iters = mini_stochastic_iters
        self.shuffle_per_iter = shuffle_per_iter
        self.partition_shuffles = max(1, partition_shuffles)
        self.verbose = verbose
        self.loss_callback = loss_callback
        self.dropout_name = dropout_name
        self.mesh = mesh
        self.seed = seed
        # rng_impl='rbg' swaps the dropout/shuffle key stream to the TPU's
        # hardware PRNG (typed keys carry their impl through split/fold_in/
        # bernoulli): threefry mask generation is pure VPU overhead on the
        # training step — dropout-heavy transformers reclaim it. None keeps
        # JAX's default threefry stream (bit-reproducible with prior rounds).
        self.rng_impl = rng_impl
        # pipeline-parallel fits ('pp' mesh axis): microbatches per batch
        # (None = deepest power-of-two the per-replica batch divides) and
        # schedule ('gpipe' | '1f1b' | 'sequential' — parallel/pp.py)
        self.pp_microbatches = pp_microbatches
        self.pp_schedule = pp_schedule
        # ZeRO-1 weight-update sharding on pure-dp meshes (optimizers_sharded):
        # 'auto' turns on when the optimizer carries per-param state and
        # dp >= 2 (and nothing standard-layout-dependent like clip_norm /
        # ema_decay is configured); 'on' forces it where eligible (warns and
        # falls back otherwise); 'off' keeps the replicated update
        if weight_update_sharding not in ("auto", "on", "off"):
            raise ValueError(
                f"weight_update_sharding must be 'auto', 'on', or 'off'; "
                f"got {weight_update_sharding!r}")
        self.weight_update_sharding = weight_update_sharding
        # the declarative ShardingConfig (sharding.py) supersedes the legacy
        # knob when given: its zero_stage (0-3) is an explicit request —
        # ineligible fits raise instead of silently falling back — and its
        # data/dcn axes + offload flag drive the unified dp step builder.
        # None keeps the weight_update_sharding semantics above.
        self.sharding = (as_sharding_config(sharding)
                         if sharding is not None else None)
        # training strategy: None/'sync' is the synchronous mesh path below;
        # 'elastic_dp' routes fit() through parallel.elastic — bounded-
        # staleness async replicas over a versioned parameter store (the
        # reference's Hogwild identity, modernized). `elastic` tunes it:
        # replicas, max_staleness, dampening, density_threshold, lease_ttl_s.
        if strategy not in (None, "sync", "elastic_dp"):
            raise ValueError(
                f"strategy must be None, 'sync', or 'elastic_dp'; "
                f"got {strategy!r}")
        self.strategy = strategy
        self.elastic = dict(elastic or {})
        _known = {"replicas", "max_staleness", "dampening",
                  "density_threshold", "lease_ttl_s"}
        unknown = set(self.elastic) - _known
        if unknown:
            raise ValueError(
                f"unknown elastic option(s) {sorted(unknown)}; "
                f"known: {sorted(_known)}")
        if self.elastic and strategy != "elastic_dp":
            raise ValueError(
                "elastic options require strategy='elastic_dp'")
        # filled by an elastic fit: push/staleness/membership accounting
        self.last_elastic_stats: Optional[Dict[str, Any]] = None
        # debug_recompiles=True runs each fit under analysis.track_recompiles:
        # every train/epoch-step trace is counted and diffed, and the report
        # lands in self.recompile_report / self.recompile_findings
        self.debug_recompiles = bool(debug_recompiles)
        self.recompile_report: Optional[str] = None
        self.recompile_findings: list = []
        self._zero1_active = False
        self._zero_stage = 0        # resolved per fit: 0..3
        self._zero3_template = None  # standard param shapes for stage-3 fits
        self._offload_active = False
        # divergence detection: a non-finite epoch loss always WARNS
        # (post-hoc on the fused path); halt_on_nan=True additionally stops
        # the fit at that epoch, returning the state from before the NaN
        # update propagated further — it joins verbose/loss_callback/
        # checkpointing in the needs-per-epoch-host-control set, so setting
        # it takes the loop path instead of the single-dispatch fused one
        self.halt_on_nan = halt_on_nan
        self.params = None
        self._last_opt_state = None
        self._epoch_cache = {}  # (batch, num_batches, mode, shuffle) -> compiled epoch
        # step-level checkpoint/resume — a capability upgrade over the
        # reference's save-at-end-only persistence (SURVEY.md §5)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        # live weight publication (train→serve): unlike checkpoints, which
        # exist to restore *this* trainer, a publish hands the current
        # weights to serving replicas (WeightWatcher hot-swap) — so it is
        # independent of checkpoint_dir. publish_every == 0 means
        # publish-once-at-fit-end when a store is configured.
        self.publish_every = int(publish_every)
        if isinstance(publish_to, str):
            from .serving.weightstore import WeightStore
            publish_to = WeightStore(publish_to)
        self._publish_store = publish_to
        # pod-scale failure handling (SURVEY.md §5: the reference's
        # drop-the-update-and-print "is not acceptable at pod scale"):
        # with a checkpoint_dir configured, a failing epoch auto-restores the
        # last checkpoint and continues, up to resume_retries times.
        # straggler_factor (e.g. 3.0) opts into per-epoch heartbeat timing:
        # an epoch slower than factor x the running median logs a warning,
        # emits a metric, and calls straggler_callback(epoch, secs, median).
        self.resume_retries = resume_retries
        self.straggler_factor = straggler_factor
        self.straggler_callback = straggler_callback
        # Sharded-parameter training (tp/fsdp): "auto" derives PartitionSpecs
        # from the model when the mesh has tensor axes beyond 'dp'
        # (megatron rules via model.param_pspecs(), ZeRO via fsdp_pspecs);
        # an explicit pspec pytree is used as-is; None keeps params
        # replicated (pure dp). See parallel/tp.py:derive_param_pspecs.
        self.param_sharding = param_sharding
        if metrics is None:
            from .utils.metrics import default_metrics
            metrics = default_metrics
        self.metrics = metrics
        # span tracing (obs/): fit(trace_spans=...) fills these per run —
        # the Tracer holding the spans, the StepStats phase summary, and
        # the Chrome-trace path the fit exported
        self.last_tracer = None
        self.last_step_stats: Optional[dict] = None
        self.last_trace_path: Optional[str] = None
        self._tracer = None
        self._step_stats = None

    # -- batching plan ------------------------------------------------------

    def _resolve_pspecs(self):
        """PartitionSpec pytree for sharded-parameter training, or None.
        Only meaningful on a multi-device mesh with tensor axes beyond 'dp'
        (pure-dp meshes replicate params regardless)."""
        if self.mesh is None:
            return None
        if self._mesh_strategy() != "default":
            # pp/sp fits derive their placements in fit() (pp: pp_pspecs on
            # the stage layout; sp: replicated params), not from megatron/
            # ZeRO rules; _strategy_task refuses an explicit user pytree
            return None
        if self.sharding is not None and self.sharding.param_axes != "auto":
            # the declarative config's per-param placement supersedes the
            # legacy param_sharding knob: None -> replicated, a pytree ->
            # explicit PartitionSpecs ('auto' defers to the knob below)
            pa = self.sharding.param_axes
            if pa is not None and isinstance(pa, str):
                raise ValueError(
                    f"ShardingConfig.param_axes must be 'auto', None, or a "
                    f"PartitionSpec pytree; got {pa!r}")
            return pa
        if self.param_sharding is None:
            return None
        if not isinstance(self.param_sharding, str):
            return self.param_sharding  # explicit pspec pytree
        if self.param_sharding != "auto":
            raise ValueError(
                f"param_sharding must be 'auto', None, or a PartitionSpec "
                f"pytree; got {self.param_sharding!r}")
        if all(a == "dp" for a in self.mesh.axis_names):
            return None
        from .parallel.tp import derive_param_pspecs
        pspecs = derive_param_pspecs(self.model, self.mesh)
        if pspecs is None and any(a_ in self.mesh.axis_names
                                  for a_ in ("tp", "ep")):
            # refusing beats silently replicating params and letting the
            # tensor ranks compute redundant identical work
            raise ValueError(
                f"mesh axes {[a_ for a_ in self.mesh.axis_names if a_ != 'dp']} "
                f"request tensor-sharded params but "
                f"{type(self.model).__name__} publishes no param_pspecs() "
                f"(megatron rules exist for the registry transformer/resnet/"
                f"moe families); use an 'fsdp' axis instead — ZeRO specs "
                f"derive from param_specs() for any model")
        return pspecs

    def _place_params(self, params, pspecs):
        from .parallel.tp import shard_params
        return shard_params(params, self.mesh, pspecs)

    # -- pp/sp strategy dispatch -------------------------------------------
    # 'pp'/'sp' mesh axes train through the dedicated step builders
    # (parallel.pp / parallel.sp) slotted into the SAME epoch machinery via
    # its step_fn override, so strategy fits see identical shuffle/batch
    # order to the default path — this is what makes them reachable from
    # the estimator's meshShape Param (reference has no parallelism at all;
    # SURVEY.md §2.3).

    def _mesh_strategy(self) -> str:
        if self.mesh is None:
            return "default"
        axes = self.mesh.axis_names
        if "pp" in axes and "sp" in axes:
            raise ValueError(
                "a Trainer mesh cannot combine 'pp' and 'sp' axes; pick "
                "one strategy per fit (pipeline xor sequence parallelism)")
        if "pp" in axes:
            bad = [a_ for a_ in axes if a_ not in ("pp", "dp")]
            if bad:
                raise ValueError(
                    f"'pp' composes with 'dp' only; mesh also has {bad}")
            return "pp"
        if "sp" in axes:
            bad = [a_ for a_ in axes if a_ not in ("sp", "dp")]
            if bad:
                raise ValueError(
                    f"'sp' composes with 'dp' only; mesh also has {bad}")
            return "sp"
        return "default"

    def _strategy_task(self, strategy: str) -> str:
        """Validate the model/mesh/label combination for a pp or sp fit and
        return the step-builder task ('classifier' | 'lm')."""
        m = self.model
        # pipeline stages / replicated sp params are placed by the strategy
        # itself — an explicit user pytree cannot be honored, so refuse it
        # loudly rather than silently replicating
        if (self.param_sharding is not None
                and not isinstance(self.param_sharding, str)):
            raise ValueError(
                "explicit param_sharding pytrees do not apply to pp/sp "
                "strategy meshes (the strategy places its own params); "
                "drop param_sharding or use a dp/tp/fsdp/ep mesh")
        n_inputs = (len(self.input_name)
                    if isinstance(self.input_name, (list, tuple)) else 1)
        if strategy == "pp" and self.label_name is not None and n_inputs != 1:
            raise ValueError(
                "pp classifier fits take exactly one input tensor (the "
                "token ids); the pipeline step has no attention-mask path — "
                "extra inputs would be silently ignored, so refuse instead")
        if n_inputs > 2:
            raise ValueError(
                f"{strategy} fits take at most (input_ids, attention_mask); "
                f"got {n_inputs} input tensors")
        if strategy == "pp":
            if not (hasattr(m, "num_layers") and hasattr(m, "_block")):
                raise ValueError(
                    f"meshShape with a 'pp' axis trains the registry "
                    f"transformer families (stage-shardable blocks); "
                    f"{type(m).__name__} has no block structure to "
                    f"pipeline — use dp/fsdp for nn-DSL graphs")
            n_stages = self.mesh.shape["pp"]
            if m.num_layers % n_stages:
                raise ValueError(
                    f"num_layers={m.num_layers} does not divide into "
                    f"pp={n_stages} pipeline stages")
            return "lm" if self.label_name is None else "classifier"
        # sp: ring attention is causal-LM only (boundary-token exclusion
        # is next-token-loss math; see parallel/sp.py docstring)
        from .models.transformer import TransformerLM
        if not isinstance(m, TransformerLM):
            raise ValueError(
                f"meshShape with an 'sp' axis trains causal LM registry "
                f"models (ring attention over the sequence); "
                f"{type(m).__name__} is not a TransformerLM family model")
        if self.label_name is not None:
            raise ValueError(
                "'sp' fits are unsupervised next-token training "
                "(tfLabel/label_name must be None)")
        return "lm"

    def _make_strategy_step(self, strategy: str, task: str, batch: int):
        """The per-batch step_fn for the epoch machinery: wraps the pp/sp
        builder's raw step under unsharded_attention (they run their own
        shard_map; re-wrapping the kernel over the same axes is invalid)."""
        from .ops.attention import unsharded_attention
        from .parallel.mesh import mesh_axis_size
        dp = mesh_axis_size(self.mesh, "dp")
        if batch % max(dp, 1):
            raise ValueError(
                f"mini_batch_size={batch} must divide over the dp axis "
                f"(size {dp}) for a {strategy} fit")
        if strategy == "pp":
            from .parallel.pp import make_pp_train_step
            per_dp = batch // max(dp, 1)
            M = self.pp_microbatches
            if M is None:
                # auto: deepest power-of-two microbatching the per-replica
                # batch supports (bounds pipeline bubble at fixed memory)
                M = next(m for m in (8, 4, 2, 1) if per_dp % m == 0)
            elif per_dp % M:
                raise ValueError(
                    f"pp_microbatches={M} must divide the per-dp-replica "
                    f"batch {per_dp}")
            raw = make_pp_train_step(
                self.model, self.optimizer, self.mesh, n_microbatches=M,
                schedule=self.pp_schedule, task=task, _raw=True)

            def step_fn(p, o, x, y, m, r):
                ids = x[0] if isinstance(x, tuple) else x
                # lm task consumes the attention mask as token loss weights
                y_eff = (x[1] if task == "lm" and isinstance(x, tuple)
                         else y)
                with unsharded_attention():
                    return raw(p, o, ids, y_eff, r)

            return step_fn
        from .parallel.sp import make_sp_train_step
        sp = self.mesh.shape["sp"]
        raw = make_sp_train_step(self.model, self.optimizer, self.mesh,
                                 _raw=True)

        def step_fn(p, o, x, y, m, r):
            ids = x[0] if isinstance(x, tuple) else x
            amask = x[1] if isinstance(x, tuple) else y  # y carries ones
            if ids.shape[1] % sp:
                raise ValueError(
                    f"sequence length {ids.shape[1]} must divide the sp "
                    f"axis (size {sp}) for ring attention")
            with unsharded_attention():
                return raw(p, o, ids, amask, r)

        return step_fn

    def _data_axis(self) -> str:
        return (self.sharding.data_axis if self.sharding is not None
                else "dp")

    def _dp_size(self) -> int:
        from .parallel.mesh import mesh_axis_size
        return mesh_axis_size(self.mesh, self._data_axis())

    # -- ZeRO weight-update/param sharding (optimizers_sharded) -------------

    def _active_cfg(self) -> ShardingConfig:
        """The ShardingConfig in effect for the current fit: the explicit
        one when given, else the legacy knobs mapped onto a config — with
        ``zero_stage`` pinned to what :meth:`_resolve_zero_stage` decided."""
        base = (self.sharding if self.sharding is not None
                else ShardingConfig())
        return base.replace(zero_stage=self._zero_stage)

    def _resolve_zero_stage(self, strategy: str, pspecs, params) -> int:
        """Decide how much of the weight update shards over dp (zero stage
        0-3).

        Eligible: default (pure-dp) strategy, replicated params (on tp/fsdp
        meshes the opt state already shards WITH the params — a zero stage
        would be a no-op at best), and dp >= 2. The legacy
        ``weight_update_sharding`` knob maps 'off'->0 and 'on'/'auto'->1:
        'auto' additionally requires the optimizer to carry per-param state
        (there is nothing to shard for sgd) and declines when clip_norm /
        ema_decay are configured — the global-norm clip would measure only
        its shard's norm, and EMA extraction expects the standard layout.
        An explicit ``sharding=ShardingConfig(zero_stage=N)`` is a REQUEST:
        ineligible fits raise an actionable ValueError instead of silently
        falling back.
        """
        cfg_opts = self._opt_cfg or {}
        blocked = [k for k in ("clip_norm", "ema_decay") if cfg_opts.get(k)]
        eligible = (strategy == "default" and pspecs is None
                    and self.mesh is not None
                    and self._data_axis() in self.mesh.axis_names
                    and self._dp_size() >= 2)
        if self.sharding is not None:
            stage = self.sharding.zero_stage
            if stage == 0:
                return 0
            if self.mesh is None:
                raise ValueError(
                    f"sharding.zero_stage={stage} shards the update over "
                    f"mesh axis {self.sharding.data_axis!r} but the trainer "
                    f"has no mesh; pass mesh=make_mesh({{'"
                    f"{self.sharding.data_axis}': N}}) or use zero_stage=0")
            # dp-less / undersized mesh: the config's own validation message
            self.sharding.validate(self.mesh, require_data_axis=True)
            if not eligible:
                raise ValueError(
                    f"sharding.zero_stage={stage} needs a pure-dp fit with "
                    f"replicated params and {self.sharding.data_axis} >= 2 "
                    f"(got strategy={strategy!r}, sharded-params="
                    f"{pspecs is not None}, {self.sharding.data_axis}="
                    f"{self._dp_size()}); use zero_stage=0 or a "
                    f"{self.sharding.data_axis}-axis mesh")
            if blocked:
                raise ValueError(
                    f"sharding.zero_stage={stage} is incompatible with "
                    f"optimizer options {blocked}: the shard-local update "
                    f"would break their global-layout math (clip_norm "
                    f"measures a global norm; ema extraction expects the "
                    f"standard layout)")
            return stage
        mode = self.weight_update_sharding
        if mode == "off":
            return 0
        if mode == "on":
            if not eligible:
                logger.warning(
                    "weight_update_sharding='on' needs a pure-dp fit on a "
                    "mesh with dp >= 2 (got strategy=%r, sharded-params=%s, "
                    "dp=%d); training with the replicated update", strategy,
                    pspecs is not None, self._dp_size())
                return 0
            if blocked:
                logger.warning(
                    "weight_update_sharding='on' is incompatible with %s "
                    "(shard-local update would break their global-layout "
                    "math); training with the replicated update", blocked)
                return 0
            return 1
        # auto
        if not eligible or blocked:
            return 0
        from .optimizers_sharded import has_per_param_state
        return 1 if has_per_param_state(self.optimizer, params) else 0

    def _make_zero_step(self, param_template=None):
        """The per-batch step_fn for the epoch machinery: the raw unified dp
        stepper (stage 1-3) runs its own shard_map, so — exactly like the
        pp/sp strategy steps — it must run under unsharded_attention
        (re-wrapping the attention kernel over the same mesh axes is
        invalid)."""
        from .ops.attention import unsharded_attention
        from .parallel.dp import make_dp_train_step
        raw = make_dp_train_step(self.model, self.optimizer, self.mesh,
                                 self.input_name, self.label_name,
                                 sharding=self._active_cfg(),
                                 param_template=param_template, _raw=True)

        def step_fn(p, o, x, y, m, r):
            with unsharded_attention():
                return raw(p, o, x, y, m, r)

        return step_fn

    def _wrap_offload(self, epoch_fn, opt_shardings):
        """``sharding.offload_opt_state=True``: the optimizer state's home is
        host memory — double-buffered, not synchronous. The old wrapper
        serialized ``device_put → step → device_get`` every call, stalling
        the loop on a PCIe round-trip per step. Now the first call (and any
        call handed a host tree, e.g. after a checkpoint restore) uploads
        with the step's shardings so donation still sees correctly-placed
        buffers; steady-state calls recognize their own returned device tree
        and skip the re-upload entirely. The device→host copy of step t's
        updated state is *enqueued* right after the (async-dispatched) step
        program, so it completes behind step t+1's compute — checkpoint
        saves, preemption and :meth:`_flush_opt_state` then find the bytes
        already host-side instead of paying the transfer at the sync point.
        Numerics are untouched: no value ever round-trips through a lossy
        path, so losses are bitwise-equal to the on-device run. Only the
        loop paths support it (the fused multi-epoch program never returns
        to the host)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        repl = (NamedSharding(self.mesh, P())
                if self.mesh is not None else None)
        last = {"dev": None}

        def wrapped(params, opt_state, *rest):
            if opt_state is not last["dev"]:
                # cold path: first call, or a restore handed us a host tree
                place = opt_shardings if opt_shardings is not None else (
                    jax.tree.map(lambda _: repl, opt_state)
                    if repl is not None else None)
                if place is not None:
                    opt_state = jax.tree.map(jax.device_put, opt_state,
                                             place)
            params, opt_state, losses = epoch_fn(params, opt_state, *rest)
            # enqueue the D2H copy NOW: it drains while the caller
            # dispatches the next step, not when someone blocks on it
            for leaf in jax.tree.leaves(opt_state):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            last["dev"] = opt_state
            return params, opt_state, losses

        return wrapped

    def _flush_opt_state(self, opt_state):
        """Offload runs keep the working opt state device-resident between
        steps (the host mirror refreshes asynchronously via
        ``copy_to_host_async``); materialize concrete host arrays at the
        points where the state outlives the loop (``_last_opt_state``)."""
        if not self._offload_active or opt_state is None:
            return opt_state
        return jax.tree.map(np.asarray, opt_state)

    def _params_to_ckpt(self, params):
        """Checkpoints (and ``self.params`` / TrainResult) always hold the
        STANDARD param layout; stage-3 fits convert from the flat sharded
        tree. Idempotent: params already in standard shape pass through, so
        post-fit callers (ema_weights) can't double-convert."""
        if self._zero_stage < 3 or self._zero3_template is None:
            return params
        t_leaves = jax.tree.leaves(self._zero3_template)
        p_leaves = jax.tree.leaves(params)
        if all(tuple(p.shape) == tuple(t.shape)
               for p, t in zip(p_leaves, t_leaves)):
            return params
        from .optimizers_sharded import gather_zero3_params
        return gather_zero3_params(params, self._zero3_template)

    def _params_from_ckpt(self, params):
        """Restore-side inverse of :meth:`_params_to_ckpt`: re-flatten and
        re-shard standard params for THIS mesh's dp size and place them."""
        if self._zero_stage < 3:
            return params
        from .optimizers_sharded import (shard_zero3_params,
                                         zero3_param_shardings)
        dp_n = self._dp_size()
        flat = shard_zero3_params(params, dp_n)
        shards = zero3_param_shardings(flat, self.mesh, dp_n,
                                       self._data_axis())
        return jax.tree.map(jax.device_put, flat, shards)

    def _opt_to_ckpt(self, params, opt_state):
        """Checkpoints always hold the STANDARD (param-shaped) opt state, so
        directories stay interchangeable across zero stages 0-3 and mesh-
        shape changes. ``params`` may arrive in either layout (stage-3 call
        sites hold the flat tree)."""
        if not self._zero1_active:
            return opt_state
        from .optimizers_sharded import gather_zero1_state
        return gather_zero1_state(self.optimizer,
                                  self._params_to_ckpt(params), opt_state,
                                  self._dp_size())

    def _opt_from_ckpt(self, params, opt_state):
        """Restore-side inverse of :meth:`_opt_to_ckpt`: re-pad and re-shard
        the standard state for THIS mesh's dp size (which may differ from
        the writing run's) and place the shards. ``params`` must be the
        STANDARD layout (restore converts the opt state before the stage-3
        param flattening)."""
        if not self._zero1_active:
            return opt_state
        from .optimizers_sharded import place_zero1_state, shard_zero1_state
        dp_n = self._dp_size()
        return place_zero1_state(
            shard_zero1_state(self.optimizer, params, opt_state, dp_n),
            self.mesh, dp_n, self._data_axis())

    def _plan(self, n: int):
        """Resolve (mode, batch_size, num_batches) from the reference's three
        batching modes (``sparkflow/HogwildSparkModel.py:62-92``)."""
        dp = self._dp_size()
        bs = self.mini_batch_size
        stochastic = bool(self.mini_stochastic_iters and self.mini_stochastic_iters > 0)
        if bs is None or bs <= 0 or (bs >= n and not stochastic):
            # full-batch mode; an over-large miniBatchSize degenerates to one
            # full-batch step per epoch...
            batch = -(-n // dp) * dp
            return "full", batch, 1
        if bs >= n:
            # ...except in stochastic mode, where the reference clamps the
            # batch to the dataset and still runs the requested number of
            # steps per epoch (sparkflow/ml_util.py:105-106)
            bs = n
        batch = -(-bs // dp) * dp  # round batch up to a multiple of dp shards
        sweeps = -(-n // batch)
        if self.mini_stochastic_iters and self.mini_stochastic_iters > 0:
            # exactly miniStochasticIters random batches per epoch, even past
            # one sweep of the data (reference ml_util.py:121-127 semantics)
            return "stochastic", batch, self.mini_stochastic_iters
        return "sweep", batch, sweeps

    # -- fit ----------------------------------------------------------------

    def _make_rng(self):
        """Root key for this fit: default threefry, or a typed key on the
        configured ``rng_impl`` (e.g. 'rbg' — see __init__)."""
        if self.rng_impl:
            return jax.random.key(self.seed, impl=self.rng_impl)
        return jax.random.PRNGKey(self.seed)

    def _restore_rng(self, raw, saved_impl=None):
        """Inverse of _ckpt_state's key handling: re-wrap raw key data under
        the configured impl. ``saved_impl`` is the checkpoint's recorded impl
        name (ASCII uint8 array) — compared exactly, so even same-width swaps
        like 'rbg' vs 'unsafe_rbg' fail with an actionable error instead of
        silently continuing on a different key stream. The key-data width
        check remains as a backstop for pre-schema checkpoints."""
        raw = jnp.asarray(raw)
        mine = self.rng_impl or "threefry"
        if saved_impl is not None:
            try:
                theirs = np.asarray(saved_impl,
                                    dtype=np.uint8).tobytes().decode()
            except UnicodeDecodeError:
                raise ValueError(
                    "checkpoint rng_impl record is not valid ASCII — the "
                    "checkpoint is corrupt; point checkpoint_dir at a fresh "
                    "directory to restart the rng stream") from None
            if theirs != mine:
                raise ValueError(
                    f"checkpoint was written under rng_impl={theirs!r} but "
                    f"this trainer is configured with rng_impl={mine!r}: "
                    f"resume with the matching rng_impl, or point "
                    f"checkpoint_dir at a fresh directory to restart the "
                    f"rng stream")
        expect = 4 if self.rng_impl in ("rbg", "unsafe_rbg") else 2
        got = raw.shape[-1] if raw.ndim else None
        if got != expect:
            raise ValueError(
                f"checkpoint rng has {got} key-data words but rng_impl="
                f"{self.rng_impl!r} expects {expect}: this checkpoint_dir was "
                f"written under a different rng_impl — resume with the "
                f"matching rng_impl, or point checkpoint_dir at a fresh "
                f"directory to restart the rng stream")
        if self.rng_impl:
            return jax.random.wrap_key_data(raw, impl=self.rng_impl)
        return raw

    def _ckpt_restore(self, ckpt_mgr, ckpt_like):
        """``ckpt_mgr.restore`` with pre-schema back-compat: checkpoints
        written before the ``rng_impl`` leaf existed fail a template restore
        that includes it (orbax raises an opaque structure-mismatch error),
        so retry without the leaf — _restore_rng's key-data width check then
        covers the impl validation for those legacy checkpoints."""
        try:
            return ckpt_mgr.restore(like=ckpt_like)
        except Exception as e:
            # only fall back when the SAVED tree genuinely lacks the leaf —
            # a new-schema checkpoint whose restore failed for a real reason
            # (corruption, shape change) must surface its original error,
            # not silently skip the exact-impl validation
            try:
                raw = ckpt_mgr.restore()
            except Exception:
                raise e
            if not isinstance(raw, dict) or "rng_impl" in raw:
                raise e
            logger.warning(
                "checkpoint in %s predates the rng_impl schema; restoring "
                "without it (impl validated by key-data width only)",
                self.checkpoint_dir)
            # a templated re-read is required (not the raw dict): the
            # template restores typed structure — opt_state NamedTuples
            # come back as plain dicts on the untemplated path
            legacy_like = {k: v for k, v in ckpt_like.items()
                           if k != "rng_impl"}
            return ckpt_mgr.restore(like=legacy_like)

    @contextlib.contextmanager
    def _recompile_scope(self):
        """With ``debug_recompiles``, run the fit under
        :func:`~sparkflow_tpu.analysis.runtime_guards.track_recompiles` and
        keep the tracker's report/findings on the trainer afterwards."""
        if not self.debug_recompiles:
            yield
            return
        from .analysis.runtime_guards import track_recompiles
        with track_recompiles() as tracker:
            try:
                yield
            finally:
                self.recompile_report = tracker.report()
                self.recompile_findings = tracker.findings()

    def fit(self, features, labels: Optional[np.ndarray] = None,
            init_params=None, *, trace_spans=False,
            trace_dir: Optional[str] = None) -> TrainResult:
        """Train. With ``trace_spans`` truthy, the fit runs instrumented:
        per-step phase spans (transfer / compile / steady step / metrics /
        checkpoint) are collected on a fresh :class:`~sparkflow_tpu.obs.Tracer`
        and exported as Chrome-trace JSON + span JSONL (``trace_spans`` may
        be the output path; otherwise one is derived from ``trace_dir``,
        ``checkpoint_dir``, or the system temp dir — see
        ``self.last_trace_path``). Phase totals and throughput/MFU gauges
        land in ``self.last_step_stats`` and the metrics registry. Tracing
        forces the per-epoch loop path (the fused multi-epoch program has
        no host-visible step boundaries to time)."""
        with self._recompile_scope():
            if not trace_spans:
                return self._fit_impl(features, labels, init_params)
            from .obs import StepStats, Tracer
            tracer = Tracer()
            stats = StepStats(tracer=tracer, metrics=self.metrics)
            self.last_tracer = tracer
            self.last_step_stats = None
            self._tracer, self._step_stats = tracer, stats
            try:
                # activate(): checkpoint/retry spans fired deep in the
                # stack route to this fit's tracer, nested under the root.
                # An ambient trace tracker must exist for the compile-vs-
                # steady probe-count delta; reuse the debug_recompiles one
                # when present (probes record to the innermost tracker
                # only — pushing a second would starve the user's report)
                from .analysis.runtime_guards import (_current_tracker,
                                                      track_recompiles)
                with contextlib.ExitStack() as es:
                    if _current_tracker() is None:
                        es.enter_context(track_recompiles(warn_after=10**9))
                    es.enter_context(tracer.activate())
                    es.enter_context(tracer.span("train/fit"))
                    result = self._fit_impl(features, labels, init_params)
            finally:
                self._tracer = None
                self._step_stats = None
            self.last_step_stats = stats._summary
            if isinstance(trace_spans, str):
                path = trace_spans
            else:
                base = trace_dir or self.checkpoint_dir or tempfile.gettempdir()
                path = os.path.join(
                    base, f"sparkflow_tpu_trace_{os.getpid()}.json")
            self.last_trace_path = tracer.export_chrome_trace(path)
            tracer.export_jsonl(
                (path[:-5] if path.endswith(".json") else path) + ".jsonl")
            return result

    def _fit_impl(self, features, labels: Optional[np.ndarray] = None,
                  init_params=None) -> TrainResult:
        # multi-input features travel as a TUPLE of arrays; a plain list is
        # row data (np.asarray coercible), exactly as in single-input fits
        multi = isinstance(features, tuple)
        n_inputs = (len(self.input_name)
                    if isinstance(self.input_name, (list, tuple)) else 1)
        if multi != (n_inputs > 1) or (multi and len(features) != n_inputs):
            got = (f"a tuple of {len(features)} arrays" if multi
                   else "a single features array")
            raise ValueError(
                f"model takes {n_inputs} input tensor(s) "
                f"({self.input_name}) but fit() got {got}")
        if multi:
            features = tuple(np.ascontiguousarray(f, dtype=np.float32)
                             for f in features)
            n = features[0].shape[0]
            if any(f.shape[0] != n for f in features):
                raise ValueError("multi-input feature arrays disagree on rows")
        else:
            features = np.ascontiguousarray(features, dtype=np.float32)
            n = features.shape[0]
        if n == 0:
            raise ValueError("no training data")
        if labels is not None:
            labels = np.ascontiguousarray(labels, dtype=np.float32)
            if labels.ndim == 1:
                labels = labels[:, None]

        if self.strategy == "elastic_dp":
            return self._fit_elastic(features, labels, init_params,
                                     multi=multi)

        strategy = self._mesh_strategy()
        task = self._strategy_task(strategy) if strategy != "default" else None
        if strategy != "default":
            # pp/sp steps have no padded-row masking: every batch must be
            # all-real rows. Trim the dataset to whole batches (stochastic
            # batches sample real rows only, so just the dp-rounding must
            # fit inside n).
            dp = self._dp_size()
            bs = self.mini_batch_size
            stoch = bool(self.mini_stochastic_iters
                         and self.mini_stochastic_iters > 0)
            if bs is None or bs <= 0 or bs >= n:
                unit = dp
            elif stoch:
                unit = dp
            else:
                unit = -(-bs // dp) * dp  # the planned sweep batch
            n_use = (n // unit) * unit
            if n_use == 0:
                raise ValueError(
                    f"dataset of {n} rows is smaller than one {strategy} "
                    f"batch ({unit} rows)")
            if n_use != n:
                logger.warning(
                    "%s fit drops the %d-row remainder (pp/sp steps carry "
                    "no padded-row masking); a miniBatchSize dividing %d "
                    "trains on every row", strategy, n - n_use, n)
                n = n_use
                features = (tuple(f[:n] for f in features) if multi
                            else features[:n])
                if labels is not None:
                    labels = labels[:n]

        mode, batch, num_batches = self._plan(n)
        if strategy != "default" and batch > n:
            raise ValueError(
                f"mini_batch_size rounds to {batch} rows (> dataset {n}); "
                f"{strategy} fits cannot pad batches — lower miniBatchSize")
        # the padded dataset always covers exactly ceil(n/batch) windows; in
        # stochastic mode num_batches may exceed that (resampled permutations)
        total = -(-n // batch) * batch
        # strategy steps have NO padded-row masking: sweep/full epochs must
        # be pad-free after the trim (stochastic mode is exempt — its
        # batches sample indices from the n REAL rows only, so the padded
        # tail is never read). Guards the trim-unit/_plan rounding coupling:
        # if they ever diverge, fail here instead of training on padding.
        if strategy != "default" and mode != "stochastic" and total != n:
            raise RuntimeError(
                f"{strategy} fit planned {total} padded rows over {n} real "
                f"ones — internal trim/_plan divergence, please report")
        if multi:
            padded = [pad_to_batches(f, batch, total // batch)
                      for f in features]
            x_pad, mask = tuple(p for p, _ in padded), padded[0][1]
        else:
            x_pad, mask = pad_to_batches(features, batch, total // batch)
        if labels is not None:
            y_pad, _ = pad_to_batches(labels, batch, total // batch)
        elif task == "lm" and not multi:
            # unsupervised pp-lm/sp fits consume the label slot as the
            # attention mask (token loss weights); single-input means no
            # mask column -> every token weighs 1
            y_pad = np.ones((total, features.shape[1]), np.float32)
        else:
            y_pad = np.zeros((total, 1), np.float32)  # dummy; loss ignores it

        rng = self._make_rng()
        init_rng, rng = jax.random.split(rng)
        if init_params is not None:
            # copy: the epoch program donates its params buffers, which would
            # invalidate the caller's arrays on TPU
            params = jax.tree.map(lambda a: jnp.array(a), init_params)
        else:
            params = self.model.init(init_rng)
        if strategy == "pp":
            # repack into the stage-stacked pipeline layout, sharded over
            # 'pp' (merged back to the standard layout at the end of fit,
            # so serving/weights export never see pipeline internals)
            from .parallel.pp import pp_pspecs, split_stage_params
            params = split_stage_params(self.model, params,
                                        self.mesh.shape["pp"])
            pspecs = pp_pspecs(params)
        else:
            pspecs = self._resolve_pspecs()
        if pspecs is not None:
            # tp/fsdp: place params per their PartitionSpecs BEFORE the
            # optimizer init so mu/nu/etc inherit the same placement
            params = self._place_params(params, pspecs)
        self._zero_stage = self._resolve_zero_stage(strategy, pspecs, params)
        self._zero1_active = self._zero_stage >= 1
        self._zero3_template = None
        self._offload_active = bool(self.sharding is not None
                                    and self.sharding.offload_opt_state
                                    and self.mesh is not None)
        opt_shardings = None
        param_shardings = None
        if self._zero1_active:
            # ZeRO: the state is built in the flat [dp, s]-leaf layout and
            # physically sharded over dp; the epoch program pins that
            # placement (opt_shardings) so donation round-trips keep it.
            # The layout is IDENTICAL for stages 1-3 (init over flat params
            # == init over standard params), so checkpoints interchange.
            from .optimizers_sharded import (place_zero1_state, sharded_update,
                                             zero1_state_shardings)
            dp_n = self._dp_size()
            dp_ax = self._data_axis()
            wrapped = sharded_update(self.optimizer, dp_n, dp_ax)
            opt_state = place_zero1_state(wrapped.init(params), self.mesh,
                                          dp_n, dp_ax)
            opt_shardings = zero1_state_shardings(opt_state, self.mesh, dp_n,
                                                  dp_ax)
            if self._zero_stage >= 3:
                # ZeRO-3: params live at rest in the flat [dp, s] layout,
                # row-sharded like the opt state; the standard-shape
                # template drives the JIT gather and checkpoint conversion
                from .optimizers_sharded import (shard_zero3_params,
                                                 zero3_param_shardings)
                self._zero3_template = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
                params = shard_zero3_params(params, dp_n)
                param_shardings = zero3_param_shardings(params, self.mesh,
                                                        dp_n, dp_ax)
                params = jax.tree.map(jax.device_put, params, param_shardings)
        else:
            opt_state = self.optimizer.init(params)

        ckpt_mgr = None
        start_epoch = 0
        ckpt_like = None
        if self.checkpoint_dir:
            from .checkpoint import CheckpointManager
            ckpt_mgr = CheckpointManager(self.checkpoint_dir)
            # host-side structural template, captured BEFORE any donation can
            # invalidate device buffers (restore-after-failure needs it)
            std_p = self._params_to_ckpt(params)
            ckpt_like = jax.tree.map(
                np.asarray, _ckpt_state(std_p,
                                        self._opt_to_ckpt(std_p, opt_state),
                                        0, rng, rng_impl=self.rng_impl))
            state = self._ckpt_restore(ckpt_mgr, ckpt_like)
            if state is not None:
                params = jax.tree.map(jnp.asarray, state["params"])
                opt_state = self._opt_from_ckpt(
                    params, jax.tree.map(jnp.asarray, state["opt_state"]))
                if pspecs is not None:
                    # restored arrays are host-loaded; re-place params (the
                    # opt state re-places lazily via inferred shardings on
                    # the first compiled step after resume)
                    params = self._place_params(params, pspecs)
                # checkpoints hold the STANDARD layout; stage 3 re-flattens
                # and re-shards for THIS mesh's dp size
                params = self._params_from_ckpt(params)
                start_epoch = int(state["epoch"])
                rng = self._restore_rng(state["rng"], state.get("rng_impl"))
                logger.info("resumed from checkpoint at epoch %d", start_epoch)

        # Stage the dataset on device(s) once; every epoch runs fully on-device.
        stats = self._step_stats  # set by fit(trace_spans=...), else None
        if stats is not None:
            # everything up to here (validation, plan, init, restore) is
            # one-time setup; charging it keeps phase sums ≈ wall time
            stats.add("setup", stats.elapsed_s())
            # sync inside the phase so host->device transfer is charged
            # here and not to the first step
            with stats.phase("transfer"):
                device_args = (jax.tree.map(jnp.asarray, x_pad),
                               jnp.asarray(y_pad), jnp.asarray(mask))
                jax.block_until_ready(device_args)
        else:
            device_args = (jax.tree.map(jnp.asarray, x_pad),
                           jnp.asarray(y_pad), jnp.asarray(mask))

        loss_by_it = {}  # device scalars; converted lazily to keep async dispatch
        t0 = time.perf_counter()
        it = 0
        ran = 0
        total_epochs = self.partition_shuffles * self.iters
        retries_left = self.resume_retries if ckpt_mgr is not None else 0
        epoch_secs = []  # straggler heartbeat history (opt-in)

        # FAST PATH: nothing host-side needs per-epoch control -> run every
        # remaining epoch as ONE compiled program (lax.scan over the epoch
        # body; single device dispatch for the whole fit). Per-epoch rngs are
        # generated exactly like the loop below, so losses match it.
        if strategy != "default":
            step_fn = self._make_strategy_step(strategy, task, batch)
        elif self._zero1_active:
            step_fn = self._make_zero_step(
                param_template=self._zero3_template)
        else:
            step_fn = None
        k = total_epochs - start_epoch
        # span tracing joins the needs-per-epoch-host-control set: the fused
        # program is one opaque dispatch with no step boundaries to time
        # (and opt-state offload needs the per-epoch call boundary to
        # refresh its host mirror)
        if (k > 1 and not self.verbose and self.loss_callback is None
                and ckpt_mgr is None and not self.straggler_factor
                and not self.halt_on_nan and stats is None
                and not self._offload_active
                and not (self._publish_store is not None
                         and self.publish_every > 0)):
            fkey = ("fused", batch, num_batches, mode, self.shuffle_per_iter,
                    n if mode == "stochastic" else None, k,
                    pspecs is not None, strategy,
                    self.pp_schedule, self.pp_microbatches,
                    self._zero_stage)
            if fkey not in self._epoch_cache:
                loss_fn = make_loss_fn(self.model, self.input_name,
                                       self.label_name)
                self._epoch_cache[fkey] = make_multi_epoch_fn(
                    loss_fn, self.optimizer, batch, num_batches, mode,
                    self.shuffle_per_iter, k, self.mesh, n_real=n,
                    infer_params=pspecs is not None, step_fn=step_fn,
                    opt_shardings=opt_shardings,
                    param_shardings=param_shardings,
                    sharding=self.sharding)
            erngs = []
            for _ in range(k):
                rng, erng = jax.random.split(rng)
                erngs.append(erng)
            params, opt_state, losses = self._epoch_cache[fkey](
                params, opt_state, *device_args, jnp.stack(erngs))
            params = jax.block_until_ready(params)
            wall = time.perf_counter() - t0
            per_epoch = num_batches * batch if mode == "stochastic" else n
            if strategy == "pp":
                from .parallel.pp import merge_stage_params
                params = merge_stage_params(self.model, params)
            params = self._params_to_ckpt(params)
            self.params = params
            self._last_opt_state = opt_state
            epoch_losses = [float(l) for l in jnp.mean(losses, axis=1)]
            self._warn_non_finite(epoch_losses)
            if self._publish_store is not None:
                self._publish_weights(params)
            return TrainResult(params, epoch_losses,
                               per_epoch * k / max(wall, 1e-9), wall)

        cache_key = (batch, num_batches, mode, self.shuffle_per_iter,
                     n if mode == "stochastic" else None, pspecs is not None,
                     strategy, self.pp_schedule, self.pp_microbatches,
                     self._zero_stage)
        if cache_key not in self._epoch_cache:
            loss_fn = make_loss_fn(self.model, self.input_name, self.label_name)
            self._epoch_cache[cache_key] = make_epoch_fn(
                loss_fn, self.optimizer, batch, num_batches, mode,
                self.shuffle_per_iter, self.mesh, n_real=n,
                infer_params=pspecs is not None, step_fn=step_fn,
                opt_shardings=opt_shardings,
                param_shardings=param_shardings, sharding=self.sharding)
        epoch_fn = self._epoch_cache[cache_key]
        if self._offload_active:
            epoch_fn = self._wrap_offload(epoch_fn, opt_shardings)

        if stats is not None:
            # compile-vs-steady detection: the core trace probes record
            # every XLA trace on the ambient tracker (fit(trace_spans=...)
            # guarantees one is active); a probe-count delta across the
            # epoch call means that call paid a compile
            from .analysis.runtime_guards import _current_tracker
            stats.examples_per_step = (num_batches * batch
                                       if mode == "stochastic" else n)

            def _probe_count() -> int:
                tr = _current_tracker()
                return sum(tr.traces.values()) if tr is not None else 0

        from .utils.preempt import NullGuard, PreemptionGuard
        guard = PreemptionGuard() if ckpt_mgr is not None else NullGuard()
        preempted = False
        nan_halted = False
        with guard:
          while True:
            try:
                it = 0
                for _round in range(self.partition_shuffles):
                    for _epoch in range(self.iters):
                        if guard.requested:
                            # preemption (SIGTERM): save and stop cleanly;
                            # the next fit on this checkpoint_dir resumes
                            # here. max(it, start_epoch): during the resume
                            # skip phase `it` is behind the restored state —
                            # labeling below start_epoch would regress the
                            # checkpoint
                            at = max(it, start_epoch)
                            std_p = self._params_to_ckpt(params)
                            ckpt_mgr.save(
                                at, _ckpt_state(std_p,
                                                self._opt_to_ckpt(std_p, opt_state),
                                                at, rng, rng_impl=self.rng_impl))
                            logger.warning(
                                "preempted: checkpoint saved at epoch %d", at)
                            preempted = True
                            break
                        it += 1
                        if it <= start_epoch:
                            # the restored rng was saved AFTER these epochs'
                            # splits — skip without touching it so the stream
                            # continues exactly where the interrupted run
                            # left off
                            continue
                        te = time.perf_counter()
                        rng, erng = jax.random.split(rng)
                        if stats is None:
                            params, opt_state, losses = epoch_fn(
                                params, opt_state, *device_args, erng)
                        else:
                            stats.begin_step()
                            probes_before = _probe_count()
                            ts0 = time.perf_counter()
                            params, opt_state, losses = epoch_fn(
                                params, opt_state, *device_args, erng)
                            # sync so the step phase owns its real device
                            # time (async dispatch would smear it into the
                            # metrics/checkpoint phases)
                            jax.block_until_ready((params, losses))
                            ts1 = time.perf_counter()
                            step_compiled = _probe_count() > probes_before
                            pname = ("step_compile" if step_compiled
                                     else "step")
                            stats.add(pname, ts1 - ts0)
                            self._tracer.record(
                                f"train/{pname}", ts0, ts1,
                                parent=self._tracer.current(),
                                args={"epoch": it})
                        loss_by_it[it] = jnp.mean(losses)
                        ran += 1
                        needs_loss_val = (self.halt_on_nan or self.verbose
                                          or self.loss_callback is not None)
                        with (stats.phase("metrics") if stats is not None
                              else contextlib.nullcontext()):
                            loss_val = (float(loss_by_it[it])  # ONE device sync
                                        if needs_loss_val else None)
                            if self.halt_on_nan and not np.isfinite(loss_val):
                                logger.error(
                                    "non-finite loss %r at epoch %d: halting "
                                    "(halt_on_nan=True); check the learning "
                                    "rate / input data, or resume from the "
                                    "last finite checkpoint", loss_val, it)
                                nan_halted = True
                                preempted = True  # reuse the clean-stop path
                                break
                            if self.verbose or self.loss_callback is not None:
                                if self.verbose:
                                    logger.info("iteration %d loss %f", it,
                                                loss_val)
                                self.metrics.scalar("train/loss", loss_val,
                                                    step=it)
                                if self.loss_callback is not None:
                                    # reference signature: loss_callback(loss,
                                    # iteration, partition_id) —
                                    # HogwildSparkModel.py:99-100; one logical
                                    # partition here.
                                    self.loss_callback(loss_val, it, 0)
                        if self.straggler_factor:
                            jax.block_until_ready(loss_by_it[it])
                            secs = time.perf_counter() - te
                            if len(epoch_secs) >= 3:
                                med = float(np.median(epoch_secs))
                                if secs > self.straggler_factor * med:
                                    logger.warning(
                                        "straggling epoch %d: %.3fs vs "
                                        "median %.3fs", it, secs, med)
                                    self.metrics.scalar("train/straggler_secs",
                                                        secs, step=it)
                                    if self.straggler_callback is not None:
                                        self.straggler_callback(it, secs, med)
                            epoch_secs.append(secs)
                        if (ckpt_mgr is not None and self.checkpoint_every > 0
                                and (it % self.checkpoint_every == 0
                                     or it == total_epochs)):
                            with (stats.phase("checkpoint")
                                  if stats is not None
                                  else contextlib.nullcontext()):
                                std_p = self._params_to_ckpt(params)
                                ckpt_mgr.save(
                                    it, _ckpt_state(
                                        std_p,
                                        self._opt_to_ckpt(std_p, opt_state),
                                        it, rng, rng_impl=self.rng_impl))
                        if (self._publish_store is not None
                                and self.publish_every > 0
                                and (it % self.publish_every == 0
                                     or it == total_epochs)):
                            self._publish_weights(self._params_to_ckpt(params))
                        if stats is not None:
                            stats.end_step(compiled=step_compiled)
                    if preempted:
                        break
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # pod-scale failure handling: restore the last checkpoint and
                # keep training (the reference dropped the update and printed,
                # HogwildSparkModel.py:68-92 — unacceptable per SURVEY.md §5)
                state = (self._ckpt_restore(ckpt_mgr, ckpt_like)
                         if retries_left > 0 else None)
                if state is None:
                    raise
                retries_left -= 1
                params = jax.tree.map(jnp.asarray, state["params"])
                opt_state = self._opt_from_ckpt(
                    params, jax.tree.map(jnp.asarray, state["opt_state"]))
                params = self._params_from_ckpt(params)
                start_epoch = int(state["epoch"])
                rng = self._restore_rng(state["rng"], state.get("rng_impl"))
                # epochs past the restore point will re-run: drop their losses
                loss_by_it = {k: v for k, v in loss_by_it.items()
                              if k <= start_epoch}
                logger.warning(
                    "training failure at iteration %d (%s: %s); auto-resumed "
                    "from checkpoint epoch %d (%d retries left)", it,
                    type(e).__name__, e, start_epoch, retries_left)
        # block until the last step is done for honest timing
        params = jax.block_until_ready(params)
        wall = time.perf_counter() - t0
        if stats is not None:
            # FLOPs per "step" (= one epoch_fn call = num_batches optimizer
            # steps) via XLA cost analysis; best-effort — it compiles a
            # probe step (clock stopped first so that compile doesn't
            # inflate the fit's wall time), and some strategies/backends
            # can't price it
            stats.stop_clock()
            flops = None
            if not multi:
                try:
                    from .utils.flops import train_step_flops
                    per_batch = train_step_flops(
                        self.model, self.input_name, self.label_name,
                        self.optimizer, x_pad[:batch], y_pad[:batch])
                    if per_batch:
                        flops = per_batch * num_batches
                except Exception:
                    flops = None
            stats.finalize(flops_per_step=flops)
        # real examples per epoch: padded rows carry zero weight and don't
        # count; stochastic mode counts sampled slots (its actual step volume)
        per_epoch = num_batches * batch if mode == "stochastic" else n
        seen = per_epoch * ran
        if strategy == "pp":
            from .parallel.pp import merge_stage_params
            params = merge_stage_params(self.model, params)
        params = self._params_to_ckpt(params)
        self.params = params
        self._last_opt_state = self._flush_opt_state(opt_state)
        epoch_keys = sorted(loss_by_it)
        epoch_losses = [float(loss_by_it[k]) for k in epoch_keys]
        if not nan_halted:  # the halt already logged its own ERROR
            self._warn_non_finite(epoch_losses, epoch_keys)
        stop = ("nan" if nan_halted
                else "preempted" if preempted else "completed")
        # publish-at-end mode (publish_every == 0): the fit's final weights
        # become the next served version — but never NaN-halted ones
        if (self._publish_store is not None and self.publish_every <= 0
                and not nan_halted):
            self._publish_weights(params)
        return TrainResult(params, epoch_losses, seen / max(wall, 1e-9), wall,
                           stop_reason=stop)

    def _publish_weights(self, std_params) -> None:
        """Best-effort push of standard-layout weights to the configured
        :class:`~sparkflow_tpu.serving.weightstore.WeightStore`. A failed
        publication logs and moves on — it must never fail training, and
        serving replicas keep last-good weights regardless."""
        try:
            v = self._publish_store.publish(std_params)
            logger.info("trainer: published weights as version %d", v)
        except Exception:
            logger.exception("trainer: live weight publication failed")

    def _fit_elastic(self, features, labels, init_params,
                     multi: bool) -> TrainResult:
        """strategy='elastic_dp': train through
        :class:`~sparkflow_tpu.parallel.elastic.ElasticDPEngine` — N replica
        threads over round-robin data shards, exchanging gradients through
        the bounded-staleness versioned store instead of a sync all-reduce.
        Reference semantics preserved: per-replica batch is miniBatchSize
        and each replica makes ``iters`` passes over its shard per shuffle
        round, like the reference's per-partition workers."""
        if multi:
            raise ValueError(
                "strategy='elastic_dp' supports single-input models only "
                "(multi-input gradient exchange is not implemented); use "
                "the sync path")
        if self.checkpoint_dir:
            logger.warning(
                "strategy='elastic_dp' ignores checkpoint_dir: the async "
                "store has no epoch boundary to checkpoint at (resume "
                "support is a sync-path feature)")

        from .core import make_loss_fn
        from .parallel.elastic import ElasticDPEngine

        n = features.shape[0]
        replicas = int(self.elastic.get("replicas", 4))
        if replicas < 1:
            raise ValueError(f"elastic replicas must be >= 1, got {replicas}")
        replicas = min(replicas, n)  # every replica needs at least one row

        rng = self._make_rng()
        init_rng, _rng = jax.random.split(rng)
        if init_params is not None:
            params = jax.tree.map(lambda a: jnp.array(a), init_params)
        else:
            params = self.model.init(init_rng)

        # engine calls back as (loss, replica_step, replica_index) — the
        # same shape as the sync path's (loss, iteration, partition_id)
        engine = ElasticDPEngine(
            make_loss_fn(self.model, self.input_name, self.label_name),
            self.optimizer, params,
            max_staleness=int(self.elastic.get("max_staleness", 4)),
            dampening=self.elastic.get("dampening", "inverse"),
            density_threshold=self.elastic.get("density_threshold", 0.25),
            lease_ttl_s=float(self.elastic.get("lease_ttl_s", 10.0)),
            metrics=self.metrics, loss_callback=self.loss_callback,
            publish_to=self._publish_store, publish_every=self.publish_every)

        shards = [(features[i::replicas],
                   labels[i::replicas] if labels is not None else None)
                  for i in range(replicas)]
        # mini_batch_size <= 0 means full-batch (the sync planner's 'full'
        # mode); per replica that is its whole shard per step
        bs = self.mini_batch_size
        if bs is None or bs <= 0:
            bs = n
        epochs = max(1, self.iters) * self.partition_shuffles
        result = engine.run_threads(
            shards, epochs=epochs, batch_size=bs, seed=self.seed)

        self.params = result.params
        self._last_opt_state = result.opt_state
        self.last_elastic_stats = result.stats
        if self.verbose:
            logger.info(
                "elastic fit: %d replicas, %d accepted / %d rejected-stale "
                "/ %d dropped pushes, final version %d",
                replicas, result.stats["accepted"],
                result.stats["rejected_stale"],
                result.stats["dropped_stale"] + result.stats["dropped_fault"],
                result.version)
        if self._publish_store is not None and self.publish_every <= 0:
            self._publish_weights(result.params)
        return TrainResult(result.params, result.losses,
                           result.examples_per_sec, result.wall_s,
                           stop_reason="completed")

    def ema_weights(self):
        """The debiased Polyak-averaged weight tree from the last fit, when
        the optimizer was built with the ``ema_decay`` config key; None
        otherwise. Serve these instead of the raw final weights for the
        usual EMA quality bump."""
        if self._last_opt_state is None:
            return None
        from .optimizers import extract_ema_params
        state = self._last_opt_state
        if self._zero1_active and self.params is not None:
            # defensive: zero1 'auto' declines when ema_decay is configured,
            # but a hand-built optax chain can slip past the config gate —
            # EMA leaves then live in the flat [dp, s] layout and need the
            # standard-form conversion before extraction
            state = self._opt_to_ckpt(self.params, state)
        ema = extract_ema_params(state)
        if ema is not None and self.mesh is not None \
                and self._mesh_strategy() == "pp":
            # the pp opt state tracks the stage-stacked layout; serve the
            # standard layout like fit() does for the final weights
            from .parallel.pp import merge_stage_params
            ema = merge_stage_params(self.model, ema)
        return ema

    @staticmethod
    def _warn_non_finite(epoch_losses, epoch_numbers=None):
        """Post-hoc divergence warning. ``epoch_numbers`` labels each loss
        with its REAL epoch (a resumed run's list starts mid-stream; list
        positions would mislabel the divergence point)."""
        nums = epoch_numbers or list(range(1, len(epoch_losses) + 1))
        bad = [n for n, l in zip(nums, epoch_losses) if not np.isfinite(l)]
        if bad:
            logger.warning(
                "training diverged: non-finite loss at epoch(s) %s (of %d "
                "epochs run) — the returned weights are NaN-contaminated; "
                "lower the learning rate or enable halt_on_nan",
                bad[:5], len(epoch_losses))

    def fit_stream(self, row_iterator, init_params=None, queue_capacity: int = 8,
                   chunk: int = 1024, epochs: int = 1) -> TrainResult:
        with self._recompile_scope():
            return self._fit_stream_impl(row_iterator, init_params,
                                         queue_capacity, chunk, epochs)

    def _fit_stream_impl(self, row_iterator, init_params=None,
                         queue_capacity: int = 8, chunk: int = 1024,
                         epochs: int = 1) -> TrainResult:
        """Streaming fit for datasets that don't fit in device memory.

        ``row_iterator`` yields ``(features, label)`` pairs (bare features when
        unsupervised), or is a zero-arg callable returning a fresh such
        iterator (required when ``epochs > 1`` — streams are single-pass, so
        each epoch re-pulls the source, matching Spark's ``rdd.toLocalIterator``
        semantics). Optimizer state, the rng stream, and the loss history
        carry across epochs — multiple epochs here train identically to
        repeated passes over an in-memory dataset, not like restarted fits.

        Multi-input models (``input_name`` a sequence) stream too: each row's
        features travel as a TUPLE of vectors, ride the batch ring
        concatenated into one flat row, and are split back into per-input
        arrays before the train step.

        A native C++ batch-assembly thread (numpy fallback) pads/masks/
        shuffles fixed-shape batches concurrently with device compute; each
        batch is one synchronous optimizer step.
        """
        import itertools as _it

        from .core import make_train_step
        from .localml.linalg import vector_to_array
        from .utils.data import BatchQueue, feed_from_iterator

        if self._mesh_strategy() != "default":
            raise ValueError(
                "fit_stream trains dp/tp/fsdp/ep meshes; pp/sp strategy "
                "fits need the whole dataset staged for their fixed-shape "
                "batch schedules — use fit() (fitMode='collect')")
        multi = isinstance(self.input_name, (list, tuple))
        factory = row_iterator if callable(row_iterator) else None
        if epochs > 1 and factory is None:
            raise ValueError("epochs > 1 needs a callable iterator factory "
                             "(streams are single-pass)")

        supervised = self.label_name is not None
        rng = self._make_rng()
        init_rng, rng = jax.random.split(rng)

        bs = self.mini_batch_size if self.mini_batch_size and self.mini_batch_size > 0 else 128
        bs = -(-bs // self._dp_size()) * self._dp_size()

        if init_params is not None:
            # copy: the train step donates its params buffers
            params = jax.tree.map(lambda a: jnp.array(a), init_params)
        else:
            params = self.model.init(init_rng)
        pspecs = self._resolve_pspecs()
        if pspecs is not None:
            # streaming honors tp/fsdp sharding exactly like fit(): place
            # params first so the optimizer state inherits the placement
            params = self._place_params(params, pspecs)
        self._zero_stage = self._resolve_zero_stage("default", pspecs, params)
        self._zero1_active = self._zero_stage >= 1
        self._zero3_template = None
        self._offload_active = bool(self.sharding is not None
                                    and self.sharding.offload_opt_state
                                    and self.mesh is not None)
        opt_shardings = None
        if self._zero1_active:
            # same zero wiring as fit(): sharded state, reduce_scatter step
            # (make_dp_train_step has make_train_step's signature)
            from .optimizers_sharded import (place_zero1_state, sharded_update,
                                             zero1_state_shardings)
            from .parallel.dp import make_dp_train_step
            dp_n = self._dp_size()
            dp_ax = self._data_axis()
            wrapped = sharded_update(self.optimizer, dp_n, dp_ax)
            opt_state = place_zero1_state(wrapped.init(params), self.mesh,
                                          dp_n, dp_ax)
            opt_shardings = zero1_state_shardings(opt_state, self.mesh, dp_n,
                                                  dp_ax)
            if self._zero_stage >= 3:
                from .optimizers_sharded import (shard_zero3_params,
                                                 zero3_param_shardings)
                self._zero3_template = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
                params = shard_zero3_params(params, dp_n)
                params = jax.tree.map(
                    jax.device_put, params,
                    zero3_param_shardings(params, self.mesh, dp_n, dp_ax))
            step = make_dp_train_step(
                self.model, self.optimizer, self.mesh, self.input_name,
                self.label_name, sharding=self._active_cfg(),
                param_template=self._zero3_template)
        else:
            opt_state = self.optimizer.init(params)
            loss_fn = make_loss_fn(self.model, self.input_name,
                                   self.label_name)
            step = make_train_step(loss_fn, self.optimizer, self.mesh,
                                   infer_params=pspecs is not None,
                                   sharding=self.sharding)
        if self._offload_active:
            # streaming: per-step double-buffered offload — the host mirror
            # refreshes behind each step's compute instead of a synchronous
            # hop around every step
            step = self._wrap_offload(step, opt_shardings)

        ckpt_mgr = None
        start_step = 0
        if self.checkpoint_dir:
            # streaming checkpoint/resume: state is saved every
            # checkpoint_every STEPS; a restart restores weights + optimizer
            # state and continues on the incoming stream (streams can't
            # rewind, so previously consumed rows are not replayed)
            from .checkpoint import CheckpointManager
            ckpt_mgr = CheckpointManager(self.checkpoint_dir)
            std_p = self._params_to_ckpt(params)
            like = jax.tree.map(
                np.asarray, _ckpt_state(std_p,
                                        self._opt_to_ckpt(std_p, opt_state),
                                        0, rng, rng_impl=self.rng_impl))
            state = self._ckpt_restore(ckpt_mgr, like)
            if state is not None:
                params = jax.tree.map(jnp.asarray, state["params"])
                opt_state = self._opt_from_ckpt(
                    params, jax.tree.map(jnp.asarray, state["opt_state"]))
                if pspecs is not None:
                    params = self._place_params(params, pspecs)
                params = self._params_from_ckpt(params)
                start_step = int(state["epoch"])
                rng = self._restore_rng(state["rng"], state.get("rng_impl"))
                logger.info("fit_stream resumed weights from step %d",
                            start_step)

        losses = []
        seen = 0
        nan_halted = False
        it_count = start_step
        t0 = time.perf_counter()
        dummy_y = np.zeros((bs, 1), np.float32)
        from .utils.preempt import NullGuard, PreemptionGuard
        stream_guard = (PreemptionGuard() if ckpt_mgr is not None
                        else NullGuard())
        preempt_saved = False
        with stream_guard:
            for epoch in range(max(1, epochs)):
                if stream_guard.requested:
                    # signal landed between epochs (feeder teardown /
                    # iterator setup window): persist before stopping, same
                    # contract as the in-loop check
                    if ckpt_mgr is not None and not preempt_saved:
                        ckpt_mgr.save(it_count, _ckpt_state(
                            self._params_to_ckpt(params),
                            self._opt_to_ckpt(params, opt_state),
                            it_count, rng, rng_impl=self.rng_impl))
                        logger.warning("preempted: checkpoint saved at "
                                       "stream step %d", it_count)
                    break
                it = iter(factory() if factory else row_iterator)
                try:
                    first = next(it)
                except StopIteration:
                    raise ValueError("no training data")
                raw0 = first[0] if supervised else first
                if multi:
                    if (not isinstance(raw0, tuple)
                            or len(raw0) != len(self.input_name)):
                        got = (f"a tuple of {len(raw0)}"
                               if isinstance(raw0, tuple) else "a single vector")
                        raise ValueError(
                            f"model takes {len(self.input_name)} input "
                            f"tensors ({self.input_name}) but the stream "
                            f"yields {got} per row")
                    part_dims = [int(vector_to_array(p).shape[0])
                                 for p in raw0]
                    split_at = list(np.cumsum(part_dims))[:-1]
                    row_dim = int(sum(part_dims))
                else:
                    row_dim = int(vector_to_array(raw0).shape[0])
                if supervised:
                    lbl0 = first[1]
                    label_dim = (1 if isinstance(lbl0, (int, float))
                                 else len(vector_to_array(lbl0)))
                else:
                    label_dim = 0

                q = BatchQueue(bs, row_dim, label_dim, capacity=queue_capacity,
                               shuffle=self.shuffle_per_iter,
                               seed=self.seed + epoch)
                feeder = feed_from_iterator(q, _it.chain([first], it), supervised,
                                            chunk)
                # NOTE on overlap: the step dispatch is async (JAX enqueues the
                # computation and the arg transfers), so the device runs batch N
                # while this loop pops/assembles batch N+1 — an explicit
                # device_put lookahead would only delay step N's dispatch behind
                # the (possibly slow) pop of N+1
                try:
                    for x, y, mask, n_real in q:
                        if stream_guard.requested:
                            # preemption: persist and stop; the stream can't
                            # rewind, so unconsumed rows are not replayed (the
                            # caller's iterator factory re-pulls the source)
                            if ckpt_mgr is not None:
                                ckpt_mgr.save(it_count, _ckpt_state(
                                    self._params_to_ckpt(params),
                                    self._opt_to_ckpt(params, opt_state),
                                    it_count, rng, rng_impl=self.rng_impl))
                                preempt_saved = True
                            logger.warning("preempted: stopping stream at step "
                                           "%d", it_count)
                            # unblock the producer BEFORE feeder.join(): it
                            # may be mid-push into a full queue (close is
                            # idempotent; the finally re-calls it harmlessly)
                            q.close()
                            break
                        rng, srng = jax.random.split(rng)
                        if multi:
                            # split the concatenated ring row back into the
                            # per-input arrays the loss feeds by tensor name
                            x = tuple(np.ascontiguousarray(s) for s in
                                      np.split(x, split_at, axis=1))
                        params, opt_state, loss = step(params, opt_state, x,
                                                       y if supervised else dummy_y,
                                                       mask, srng)
                        losses.append(loss)
                        seen += n_real
                        it_count += 1
                        # opt-in: costs a per-step device sync (the loop is
                        # otherwise fully async), so only when requested
                        if self.halt_on_nan and not np.isfinite(float(loss)):
                            logger.error(
                                "non-finite loss at stream step %d: halting "
                                "(halt_on_nan=True)", it_count)
                            nan_halted = True
                            q.close()
                            break
                        if self.loss_callback is not None:
                            self.loss_callback(float(loss), it_count, 0)
                        if (ckpt_mgr is not None and self.checkpoint_every > 0
                                and it_count % self.checkpoint_every == 0):
                            ckpt_mgr.save(it_count, _ckpt_state(
                                self._params_to_ckpt(params),
                                self._opt_to_ckpt(params, opt_state),
                                it_count, rng, rng_impl=self.rng_impl))
                    feeder.join()
                    if nan_halted:
                        break
                finally:
                    # always tear the queue down (drains and unblocks the feeder);
                    # without this a failing step would leak the native ring and
                    # leave the producer thread blocked forever
                    q.close()
        params = jax.block_until_ready(params)
        wall = time.perf_counter() - t0
        params = self._params_to_ckpt(params)
        self.params = params
        self._last_opt_state = self._flush_opt_state(opt_state)
        step_losses = [float(l) for l in losses]
        if not nan_halted:  # the halt already logged its own ERROR
            self._warn_non_finite(step_losses)
        stop = ("nan" if nan_halted
                else "preempted" if stream_guard.requested else "completed")
        return TrainResult(params, step_losses, seen / max(wall, 1e-9), wall,
                           stop_reason=stop)

    # -- conveniences -------------------------------------------------------

    def weights_list(self) -> List[np.ndarray]:
        """Final weights as a flat array list (reference
        ``tensorflow_get_weights``, ``sparkflow/ml_util.py:9-13``)."""
        if self.params is None:
            raise RuntimeError("fit() has not been run")
        return params_to_list(self.model, self.params)

    def predict_fn(self, output_name: str, dropout_value: float = 1.0,
                   mesh=None) -> Callable:
        """``mesh=`` opts into dp-sharded batch inference (batches of any
        size are padded internally up to a dp multiple); default stays
        single-device. On a trainer whose params carry tp/fsdp placements,
        the program infers those shardings so the placed tree serves in
        place instead of all-gathering."""
        infer = self._resolve_pspecs() is not None and mesh is not None
        return make_predict_fn(self.model, self.input_name, output_name,
                               self.dropout_name, dropout_value, mesh=mesh,
                               infer_params=infer)
