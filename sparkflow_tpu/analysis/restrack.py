"""Runtime resource-balance tracking (GC-X605) — the dynamic twin of
:mod:`~sparkflow_tpu.analysis.lifecycle`.

The static pass proves acquire/release pairing over paths it can see; this
one audits an actual run. A :class:`ResourceTracker` keeps a per-resource
balance for the same pair registry — KV slots and their pages, batcher
admissions, pooled connections, per-entity gauge namespaces — recording the
acquisition stack each time a resource is checked out and crossing it off
on release. At the end of the run, :meth:`ResourceTracker.report` turns
every nonzero balance (and every double release) into a **GC-X605**
finding whose detail carries the stacks of the acquisitions that were
never paid back; :meth:`ResourceTracker.assert_balanced` raises with those
stacks inline. Chaos drills (``race_smoke``/``fleet_smoke``/
``scale_smoke``) run under the tracker when ``SPARKFLOW_TPU_RESTRACK=1``
(:func:`enabled`), turning every kill/drain/disconnect they already
perform into a leak oracle.

Instrumentation is drop-in and opt-in, racecheck-style: every
``instrument_*`` helper returns its argument untouched when no tracker is
installed — the production hot path pays one ``is None`` check per
*harness setup call* and nothing per operation. With a tracker active, the
helpers shadow the relevant bound methods on the *instance* (the class is
never touched), so only the audited objects pay for bookkeeping.

**Instrument before the worker threads start.** The wrappers swap instance
attributes non-atomically; a thread mid-call during instrumentation could
run the un-wrapped method and acquire a resource the tracker never sees —
the same gotcha as :func:`racecheck.instrument_object`.

Typical harness shape::

    tracker = ResourceTracker().install() if restrack.enabled() else None
    if tracker is not None:
        restrack.instrument_engine(engine)      # slots + KV pages
        restrack.instrument_batcher(batcher)    # admissions
        restrack.instrument_metrics(metrics, prefixes=("router/replica",))
    ... chaos ...
    if tracker is not None:
        tracker.assert_balanced()
        tracker.uninstall()
"""

from __future__ import annotations

import os
import re
import threading
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, Hashable, List, Optional, Sequence, Tuple

from .findings import Finding

__all__ = ["ResourceTracker", "enabled", "active", "instrument_pair",
           "instrument_engine", "instrument_pool", "instrument_batcher",
           "instrument_metrics"]

_ACTIVE: Optional["ResourceTracker"] = None


def enabled() -> bool:
    """True when the ``SPARKFLOW_TPU_RESTRACK`` env flag asks chaos/test
    harnesses to run under a tracker."""
    return os.environ.get("SPARKFLOW_TPU_RESTRACK", "") not in ("", "0")


def active() -> Optional["ResourceTracker"]:
    """The installed tracker, or None (the common, zero-overhead case)."""
    return _ACTIVE


def _site_stack() -> str:
    frames = traceback.extract_stack()
    frames = [f for f in frames if not f.filename.endswith("restrack.py")]
    return "".join(traceback.format_list(frames[-8:])).rstrip()


@dataclass
class Violation:
    """A release with no matching acquire (double free / free of something
    the tracker never saw acquired)."""
    category: str
    key: Hashable
    stack: str


class ResourceTracker:
    """Per-resource acquire/release balance for one instrumented run.

    Keys are ``(category, key)`` — e.g. ``("kv-slot", 3)``,
    ``("http-conn", id(conn))``, ``("gauge-ns", "router/replica2/healthy")``.
    Same-key re-acquisition stacks pile up (balance 2 means two unpaid
    acquires). Use as a context manager or ``install()``/``uninstall()``;
    one tracker at a time, nesting restores the outer one.
    """

    def __init__(self):
        self._mu = threading.Lock()   # raw lock: must not track itself
        self._live: Dict[Tuple[str, Hashable], List[str]] = {}
        self.violations: List[Violation] = []
        self.acquired = 0
        self.released = 0
        self._prev: Optional[ResourceTracker] = None

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "ResourceTracker":
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        _ACTIVE = self._prev
        self._prev = None

    def __enter__(self) -> "ResourceTracker":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- the pair protocol --------------------------------------------------

    def acquire(self, category: str, key: Hashable) -> None:
        stack = _site_stack()
        with self._mu:
            self._live.setdefault((category, key), []).append(stack)
            self.acquired += 1

    def release(self, category: str, key: Hashable) -> None:
        with self._mu:
            stacks = self._live.get((category, key))
            if not stacks:
                self.violations.append(
                    Violation(category, key, _site_stack()))
                return
            stacks.pop()
            if not stacks:
                del self._live[(category, key)]
            self.released += 1

    def release_if_live(self, category: str, key: Hashable) -> bool:
        """Release only if the key has unpaid acquires — for release verbs
        that are legal on an already-released resource (``truncate`` after
        ``free``, pool ``close`` after drain). Returns whether it paid one
        down."""
        with self._mu:
            if not self._live.get((category, key)):
                return False
        self.release(category, key)
        return True

    # -- results ------------------------------------------------------------

    def balance(self, category: Optional[str] = None) -> int:
        """Outstanding acquires (optionally for one category). Zero at the
        end of a clean run."""
        with self._mu:
            return sum(len(s) for (cat, _), s in self._live.items()
                       if category is None or cat == category)

    def live(self) -> Dict[Tuple[str, Hashable], List[str]]:
        """{(category, key): acquisition stacks} for everything unpaid."""
        with self._mu:
            return {k: list(v) for k, v in self._live.items()}

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        for (cat, key), stacks in sorted(self.live().items(),
                                         key=lambda kv: repr(kv[0])):
            out.append(Finding(
                "GC-X605",
                f"{cat}[{key!r}]: {len(stacks)} acquire(s) never released "
                f"by the end of the run — the acquisition stack(s) in "
                f"detail name the leak site",
                source="restrack",
                detail={"category": cat, "key": repr(key),
                        "balance": len(stacks), "stacks": stacks}))
        with self._mu:
            viols = list(self.violations)
        for v in viols:
            out.append(Finding(
                "GC-X605",
                f"{v.category}[{v.key!r}]: released with no matching "
                f"acquire (double free, or acquired before the tracker "
                f"was installed)",
                source="restrack",
                detail={"category": v.category, "key": repr(v.key),
                        "double_release": True, "stacks": [v.stack]}))
        return out

    def report(self) -> List[Finding]:
        """Alias of :meth:`findings` — the name the smokes print under."""
        return self.findings()

    def assert_balanced(self) -> None:
        """Raise AssertionError with acquisition stacks if anything is
        unbalanced."""
        fs = self.findings()
        if not fs:
            return
        parts = []
        for f in fs:
            parts.append(f.render())
            for s in f.detail.get("stacks", []):
                parts.append(_indent(str(s)))
        raise AssertionError(
            f"restrack: {len(fs)} unbalanced resource(s)\n"
            + "\n".join(parts))


def _indent(text: str, pad: str = "    ") -> str:
    return "\n".join(pad + ln for ln in text.splitlines())


# -- instrumentation ----------------------------------------------------------


def instrument_pair(obj: object, category: str, acquire: str,
                    releases: Sequence[str],
                    key_of: Callable[..., Hashable],
                    key_of_release: Optional[Callable[..., Hashable]] = None,
                    idempotent_releases: Sequence[str] = ()):
    """Generic pair wrapper (no-op without an active tracker): shadow
    ``obj.<acquire>`` and each ``obj.<release>`` with bound wrappers that
    record the balance. ``key_of(result, *args, **kw)`` maps an acquire
    call to its resource key; ``key_of_release(*args, **kw)`` (default: the
    first positional argument) maps a release call. Verbs listed in
    ``idempotent_releases`` only pay down live balances (legal on an
    already-released resource). Returns ``obj``."""
    t = _ACTIVE
    if t is None:
        return obj

    orig_acquire = getattr(obj, acquire)

    def acq_wrapper(*a, **kw):
        result = orig_acquire(*a, **kw)
        key = key_of(result, *a, **kw)
        if key is not None:
            t.acquire(category, key)
        return result

    setattr(obj, acquire, acq_wrapper)
    for rel in releases:
        orig_rel = getattr(obj, rel)
        idem = rel in idempotent_releases

        def rel_wrapper(*a, _orig=orig_rel, _idem=idem, **kw):
            key = (key_of_release(*a, **kw) if key_of_release is not None
                   else (a[0] if a else None))
            if key is not None:
                if _idem:
                    t.release_if_live(category, key)
                else:
                    t.release(category, key)
            return _orig(*a, **kw)

        setattr(obj, rel, rel_wrapper)
    return obj


def instrument_engine(engine):
    """Track decode-slot checkout on a :class:`DecodeEngine`:
    ``prefill`` acquires the slot its result names, ``release`` pays it
    back. The engine releases its KV pages inside ``release`` under its
    own lock, so slot balance == page-holding-sequence balance. No-op
    without an active tracker; returns ``engine``."""
    return instrument_pair(
        engine, "decode-slot", "prefill", ("release",),
        key_of=lambda info, *a, **kw: int(info["slot"]),
        key_of_release=lambda slot, *a, **kw: int(slot))


def instrument_pool(pool):
    """Track checkouts on a :class:`ConnectionPool`: ``acquire`` checks a
    connection out, ``release`` (either reuse flavor) returns it. No-op
    without an active tracker; returns ``pool``."""
    return instrument_pair(
        pool, "http-conn", "acquire", ("release",),
        key_of=lambda result, *a, **kw: id(result[0]),
        key_of_release=lambda conn, *a, **kw: id(conn))


def instrument_batcher(batcher):
    """Track admissions on a :class:`ContinuousBatcher`: an admission is
    acquired when ``_try_admit_locked`` pops a request and released when
    that request's future resolves — which covers every retirement path
    (normal finish, prefill failure, close/drain abandonment) because each
    of them must resolve the future for the caller to unblock. No-op
    without an active tracker; returns ``batcher``."""
    t = _ACTIVE
    if t is None:
        return batcher
    orig = batcher._try_admit_locked

    def admit_wrapper():
        req = orig()
        if req is not None:
            key = id(req)
            t.acquire("batch-slot", key)
            req.future.add_done_callback(
                lambda _f: t.release("batch-slot", key))
        return req

    batcher._try_admit_locked = admit_wrapper
    return batcher


def instrument_metrics(metrics, prefixes: Sequence[str]):
    """Track per-entity gauge namespaces on a
    :class:`~sparkflow_tpu.utils.metrics.Metrics` registry: a ``gauge()``
    whose name starts with one of ``prefixes`` and wasn't registered
    before acquires that name; ``remove_prefix``/``remove_matching``/
    ``reset`` release every tracked name they drop. Names outside
    ``prefixes`` (process-level gauges) are not tracked — only per-entity
    families must come down with their entity. No-op without an active
    tracker; returns ``metrics``."""
    t = _ACTIVE
    if t is None:
        return metrics
    prefixes = tuple(prefixes)
    seen: set = set()
    mu = threading.Lock()

    orig_gauge = metrics.gauge
    orig_remove_prefix = metrics.remove_prefix
    orig_remove_matching = getattr(metrics, "remove_matching", None)
    orig_reset = metrics.reset

    def gauge_wrapper(name, value):
        with mu:
            fresh = (name not in seen
                     and any(name.startswith(p) for p in prefixes))
            if fresh:
                seen.add(name)
        if fresh:
            t.acquire("gauge-ns", name)
        return orig_gauge(name, value)

    def _drop(names):
        with mu:
            dropped = [n for n in names if n in seen]
            seen.difference_update(dropped)
        for n in dropped:
            t.release("gauge-ns", n)

    def remove_prefix_wrapper(prefix):
        with mu:
            names = [n for n in seen if n.startswith(prefix)]
        removed = orig_remove_prefix(prefix)
        _drop(names)
        return removed

    def remove_matching_wrapper(match):
        pred = match if callable(match) else re.compile(match).search
        with mu:
            names = [n for n in seen if pred(n)]
        removed = orig_remove_matching(match)
        _drop(names)
        return removed

    def reset_wrapper():
        with mu:
            names = list(seen)
        orig_reset()
        _drop(names)

    metrics.gauge = gauge_wrapper
    metrics.remove_prefix = remove_prefix_wrapper
    if orig_remove_matching is not None:
        metrics.remove_matching = remove_matching_wrapper
    metrics.reset = reset_wrapper
    return metrics
