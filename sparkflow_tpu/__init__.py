"""sparkflow-tpu: a TPU-native deep-learning-on-Spark framework.

A brand-new JAX/XLA/pjit/pallas framework with the capabilities of
``lifeomic/sparkflow``: a Spark ML ``Estimator``/``Transformer`` pair that drops a
trainable deep-learning stage into a standard ``pyspark.ml.Pipeline``
(``fit``/``transform``/save/load preserved) — but where the reference's driver-hosted
Flask parameter server and Hogwild pickle-over-HTTP gradient exchange (reference:
``sparkflow/HogwildSparkModel.py``) are replaced by pjit-compiled train steps with XLA
all-reduce over ICI/DCN, and models ship as JSON-serialized declarative graph specs
executed by JAX instead of TF1 ``MetaGraphDef`` JSON (reference:
``sparkflow/graph_utils.py:6-15``).

Public surface (mirrors the reference module-for-module):

- :mod:`sparkflow_tpu.graph_utils`   — ``build_graph`` + optimizer config builders
- :mod:`sparkflow_tpu.nn`            — the model-definition DSL used inside
  ``build_graph`` model functions (replaces raw TF1 ops)
- :mod:`sparkflow_tpu.spark_async`   — ``SparkAsyncDL`` / ``SparkAsyncDLModel``
  (alias: :mod:`sparkflow_tpu.tensorflow_async` for drop-in imports)
- :mod:`sparkflow_tpu.hogwild`       — ``HogwildTrainer`` (the
  ``HogwildSparkModel``-shaped direct-training entry point)
- :mod:`sparkflow_tpu.pipeline_util` — ``PysparkReaderWriter`` /
  ``PysparkPipelineWrapper`` persistence
- :mod:`sparkflow_tpu.model_loader`  — pre-trained checkpoint import
- :mod:`sparkflow_tpu.parallel`      — mesh / sharding / collectives (DP, TP, SP
  ring attention; the distributed backend replacing the HTTP parameter server)
- :mod:`sparkflow_tpu.models`        — registry model zoo (MLP, CNN, autoencoder,
  ResNet, BERT)
- :mod:`sparkflow_tpu.serving`       — online inference: AOT bucket engine,
  micro-batcher, JSON-HTTP front (beyond the reference, whose only inference
  path is the offline batch transform)
- :mod:`sparkflow_tpu.resilience`    — retry policies, crash-consistent
  checkpoint verification, resumable-fit driver, deterministic fault
  injection, serving drain lifecycle (the reference's failure story was
  drop-the-update-and-print)
"""

__version__ = "0.1.0"

__all__ = [
    "graph_utils",
    "graphdef",
    "nn",
    "core",
    "trainer",
    "optimizers",
    "sharding",
    "__version__",
]
