# Developer entry points (role parity with the reference's Makefile:1-17,
# which ran the examples and tests in Docker).

.PHONY: test test-fast test-pyspark docker-test-pyspark bench bench-ladder mfu-sweep baseline examples native clean

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -x -q -k "not estimator"

# real-pyspark e2e: installs pyspark (JVM required) and runs the mirrored
# reference suite on local[2], incl. the StopWordsRemover persistence carrier
test-pyspark:
	pip install "pyspark>=3.4"
	python -m pytest tests/test_pyspark_e2e.py -v

bench:
	python bench.py

bench-quick:
	python bench.py --quick

bench-ladder:
	python benchmarks/run_all.py

mfu-sweep:
	python benchmarks/mfu_sweep.py

baseline:
	python bench_baseline.py

# PYTHONPATH must APPEND the repo root: replacing it would clobber the axon
# TPU plugin's site dir (see .claude/skills/verify/SKILL.md gotchas)
examples:
	cd examples && PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python simple_dnn.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python cnn_example.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python autoencoder_example.py

docker-test-pyspark:
	docker compose run --rm --build test-pyspark

native:
	python -c "from sparkflow_tpu.native.build import load_library; \
	           print('native lib:', load_library(verbose=True))"

clean:
	rm -rf sparkflow_tpu/native/_build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +

# round-2 example additions (text pipeline; TF1 migration needs tensorflow)
examples-extra:
	cd examples && PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python text_classifier.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python bert_classifier.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python tf1_migration.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python rnn_sequence.py
