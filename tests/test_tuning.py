"""localml.tuning: ParamGridBuilder / CrossValidator / TrainValidationSplit
(the pyspark.ml.tuning subset; the reference never built its planned
hyperparameter search — reference ``README.md:234-236``)."""

import numpy as np
import pytest

from sparkflow_tpu.localml import (
    CrossValidator, CrossValidatorModel, LocalSession,
    MulticlassClassificationEvaluator, ParamGridBuilder,
    TrainValidationSplit, Vectors)
from sparkflow_tpu.localml.base import Estimator, Model
from sparkflow_tpu.localml.param import (HasInputCol, Param, Params,
                                         TypeConverters, keyword_only)
from sparkflow_tpu.localml.sql import DataFrame, Row


@pytest.fixture(scope="module")
def spark():
    return LocalSession.builder.getOrCreate()


class _ThresholdModel(Model, HasInputCol):
    def __init__(self, threshold):
        super().__init__()
        self._t = threshold

    def _transform(self, dataset):
        rows = [Row(**{**r.asDict(),
                       "prediction": float(r["x"] > self._t)})
                for r in dataset.collect()]
        return DataFrame(rows, dataset.columns + ["prediction"],
                         dataset.num_partitions)


class _ThresholdClassifier(Estimator, HasInputCol):
    """Degenerate estimator: 'fits' nothing, classifies x > threshold.
    Grid search must recover the threshold that matches the labels."""

    threshold = Param(Params._dummy(), "threshold", "decision threshold",
                      typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, threshold=0.0):
        super().__init__()
        self._setDefault(threshold=0.0)
        self._set(**self._input_kwargs)

    def _fit(self, dataset):
        return _ThresholdModel(self.getOrDefault(self.threshold))


def _labeled_df(spark, true_threshold=2.0, n=60):
    rs = np.random.RandomState(0)
    xs = rs.uniform(0, 4, n)
    return spark.createDataFrame(
        [(float(x), float(x > true_threshold)) for x in xs], ["x", "label"])


def test_param_grid_builder():
    e = _ThresholdClassifier()
    grid = (ParamGridBuilder()
            .addGrid(e.threshold, [0.5, 1.0, 2.0])
            .build())
    assert len(grid) == 3
    assert sorted(pm[e.threshold] for pm in grid) == [0.5, 1.0, 2.0]
    # cartesian product over two params
    e2 = _ThresholdClassifier()
    grid2 = (ParamGridBuilder()
             .addGrid(e2.threshold, [0.5, 1.0])
             .baseOn({e2.inputCol: "x"})
             .build())
    assert len(grid2) == 2
    assert all(pm[e2.inputCol] == "x" for pm in grid2)


def test_cross_validator_picks_true_threshold(spark):
    df = _labeled_df(spark)
    est = _ThresholdClassifier()
    grid = ParamGridBuilder().addGrid(est.threshold,
                                      [0.5, 1.0, 2.0, 3.0]).build()
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid,
                        evaluator=MulticlassClassificationEvaluator(
                            metricName="accuracy"),
                        numFolds=3, seed=7)
    model = cv.fit(df)
    assert isinstance(model, CrossValidatorModel)
    assert len(model.avgMetrics) == 4
    assert int(np.argmax(model.avgMetrics)) == 2  # threshold=2.0 wins
    assert model.bestModel._t == 2.0
    out = model.transform(df)  # CrossValidatorModel delegates to bestModel
    acc = np.mean([r["prediction"] == r["label"] for r in out.collect()])
    assert acc == 1.0


def test_cross_validator_validation(spark):
    df = _labeled_df(spark)
    with pytest.raises(ValueError, match="needs estimator"):
        CrossValidator().fit(df)
    est = _ThresholdClassifier()
    grid = ParamGridBuilder().addGrid(est.threshold, [1.0]).build()
    with pytest.raises(ValueError, match="numFolds"):
        CrossValidator(estimator=est, estimatorParamMaps=grid,
                       evaluator=MulticlassClassificationEvaluator(),
                       numFolds=1).fit(df)


def test_train_validation_split(spark):
    df = _labeled_df(spark)
    est = _ThresholdClassifier()
    grid = ParamGridBuilder().addGrid(est.threshold,
                                      [0.5, 2.0, 3.5]).build()
    tvs = TrainValidationSplit(estimator=est, estimatorParamMaps=grid,
                               evaluator=MulticlassClassificationEvaluator(
                                   metricName="accuracy"),
                               trainRatio=0.75, seed=3)
    model = tvs.fit(df)
    assert len(model.validationMetrics) == 3
    assert model.bestModel._t == 2.0
    with pytest.raises(ValueError, match="trainRatio"):
        TrainValidationSplit(estimator=est, estimatorParamMaps=grid,
                             evaluator=MulticlassClassificationEvaluator(),
                             trainRatio=1.5).fit(df)


def test_cross_validator_over_dl_estimator(spark):
    """Grid search over SparkAsyncDL's learning rate through CrossValidator —
    the composition the reference called future work."""
    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.tensorflow_async import SparkAsyncDL

    rs = np.random.RandomState(0)
    rows = [(Vectors.dense(rs.normal(1.2 if i % 2 else -1.2, 1.0, 4)),
             float(i % 2)) for i in range(80)]
    df = spark.createDataFrame(rows, ["features", "label"])

    def m():
        x = nn.placeholder([None, 4], name="x")
        y = nn.placeholder([None, 1], name="y")
        out = nn.dense(x, 1, activation="sigmoid", name="out")
        nn.log_loss(y, out)

    est = SparkAsyncDL(inputCol="features", tensorflowGraph=build_graph(m),
                       tfInput="x:0", tfLabel="y:0", labelCol="label",
                       tfOutput="out:0", iters=20, miniBatchSize=32,
                       tfOptimizer="adam", predictionCol="rawPrediction")
    # an absurdly small lr leaves the model at its random init; a sane one
    # fits. AUC saturates at 1.0 for BOTH on data this separable (even an
    # untrained projection ranks it), so score calibration error (rmse of the
    # sigmoid output vs the 0/1 label) instead: the untrained model sits near
    # 0.5 everywhere while the trained one pushes toward the labels.
    grid = ParamGridBuilder().addGrid(est.tfLearningRate,
                                      [1e-6, 5e-2]).build()
    from sparkflow_tpu.localml import (BinaryClassificationEvaluator,
                                       RegressionEvaluator)
    tvs = TrainValidationSplit(estimator=est, estimatorParamMaps=grid,
                               evaluator=RegressionEvaluator(
                                   predictionCol="rawPrediction",
                                   labelCol="label", metricName="rmse"),
                               trainRatio=0.75, seed=0)
    model = tvs.fit(df)
    # rmse: smaller is better, so the sane lr must come out LOWER and win
    assert model.validationMetrics[1] < model.validationMetrics[0]
    # smaller-is-better argmin picked the sane-lr model as bestModel
    assert model.validationMetrics.index(
        min(model.validationMetrics)) == 1
    auc = BinaryClassificationEvaluator(labelCol="label").evaluate(
        model.transform(df))
    assert auc > 0.9


def test_grid_search_over_pipeline_stage_params(spark):
    """The standard pyspark pattern: grid keyed by a STAGE's params while
    tuning the whole Pipeline — Pipeline.copy propagates extras to stages."""
    from sparkflow_tpu.localml import Pipeline, Tokenizer

    est = _ThresholdClassifier()
    tok = Tokenizer(inputCol="text", outputCol="words")  # passthrough stage
    pipe = Pipeline(stages=[tok, est])
    df = _labeled_df(spark).withColumn(
        "text", ["x"] * _labeled_df(spark).count())
    grid = ParamGridBuilder().addGrid(est.threshold,
                                      [0.5, 2.0, 3.5]).build()
    cv = CrossValidator(estimator=pipe, estimatorParamMaps=grid,
                        evaluator=MulticlassClassificationEvaluator(
                            metricName="accuracy"),
                        numFolds=3, seed=5)
    model = cv.fit(df)
    assert int(np.argmax(model.avgMetrics)) == 1  # threshold=2.0
    assert model.bestModel.stages[-1]._t == 2.0


def test_foreign_params_ignored_on_copy():
    a, b = _ThresholdClassifier(), _ThresholdClassifier()
    copied = a.copy({b.threshold: 9.0})  # b's param: not a's to apply
    assert copied.getOrDefault(copied.threshold) == 0.0
    copied2 = a.copy({a.threshold: 9.0})
    assert copied2.getOrDefault(copied2.threshold) == 9.0
