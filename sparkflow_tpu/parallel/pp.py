"""Pipeline parallelism: transformer blocks sharded into stages over ``pp``.

Each device on the ``pp`` mesh axis holds 1/P of the transformer blocks
(stacked and sharded on a leading stage axis), so model memory scales down
with pipeline depth. Activations travel stage-to-stage with ``ppermute`` over
the ICI ring; microbatches bound activation memory and gradients accumulate
across them. Differentiation flows through the collective (ppermute transposes
to the reverse permute), so this is a complete train step, not a forward-only
demo.

Round-1 schedule note: stages execute sequentially per microbatch (a device
idles while another stage computes — the classic bubble). The 1F1B/GPipe
overlapped schedule is a scheduling optimization on top of this same layout;
the memory distribution, collectives, and numerics are already the real thing.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def split_stage_params(model, params, n_stages: int):
    """Repack transformer params into the pipeline layout:

    - ``stages``: every per-block leaf stacked to [n_stages, blocks_per_stage, ...]
      (shard the leading axis over 'pp')
    - ``shared``: embed / final_ln / head, replicated on every stage.
    """
    n_layers = model.num_layers
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} blocks not divisible by {n_stages} stages")
    per = n_layers // n_stages
    blocks = [params[f"block_{i}"] for i in range(n_layers)]
    stage_trees = []
    for s in range(n_stages):
        group = blocks[s * per:(s + 1) * per]
        stage_trees.append(jax.tree.map(lambda *xs: jnp.stack(xs), *group))
    stages = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_trees)
    # copy shared leaves: the pp train step donates its params, and aliasing
    # the caller's arrays would delete them out from under the caller
    shared = jax.tree.map(jnp.array,
                          {k: v for k, v in params.items()
                           if not k.startswith("block_")})
    return {"stages": stages, "shared": shared}


def merge_stage_params(model, pp_params):
    """Inverse of :func:`split_stage_params` (e.g. for checkpoint export)."""
    n_layers = model.num_layers
    stages = pp_params["stages"]
    flat_example = jax.tree.leaves(stages)[0]
    n_stages, per = flat_example.shape[0], flat_example.shape[1]
    assert n_stages * per == n_layers
    out = dict(pp_params["shared"])
    for i in range(n_layers):
        s, b = divmod(i, per)
        out[f"block_{i}"] = jax.tree.map(lambda x: x[s, b], stages)
    return out


def pp_pspecs(pp_params):
    """PartitionSpecs: stage axis over 'pp', shared replicated."""
    stages = jax.tree.map(lambda x: P("pp"), pp_params["stages"])
    shared = jax.tree.map(lambda x: P(), pp_params["shared"])
    return {"stages": stages, "shared": shared}


def make_pp_train_step(model, optimizer, mesh: Mesh, n_microbatches: int = 1,
                       pp_axis: str = "pp"):
    """Pipeline-parallel train step for the transformer classifier.

    Signature: ``step(pp_params, opt_state, ids, y, rng) ->
    (pp_params, opt_state, loss)`` — ids [B, S] replicated across pp (batch is
    the microbatch loop's dimension), params in :func:`split_stage_params`
    layout sharded over 'pp'.
    """
    n_stages = mesh.shape[pp_axis]
    per = model.num_layers // n_stages

    def stage_apply(stage_blocks, x, rng):
        """Apply this device's ``per`` blocks (stacked leading axis)."""

        def body(carry, block):
            x, rng = carry
            x, rng = model._block(block, x, None, False, True, rng)
            return (x, rng), None

        (x, rng), _ = jax.lax.scan(body, (x, rng), stage_blocks)
        return x

    def forward_one(pp_params, ids, y, rng):
        s = jax.lax.axis_index(pp_axis)
        shared = pp_params["shared"]
        my_blocks = jax.tree.map(lambda a: a[0], pp_params["stages"])

        ids = ids.astype(jnp.int32)
        b, seq = ids.shape
        x = jnp.take(shared["embed"]["tok"], ids, axis=0)
        x = x + shared["embed"]["pos"][:seq][None, :, :]
        x = model.cast(x)

        def tick(t, x):
            def run(x):
                return stage_apply(my_blocks, x, jax.random.fold_in(rng, t))
            x = jax.lax.cond(s == t, run, lambda x: x, x)
            return jax.lax.ppermute(
                x, pp_axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])

        x = jax.lax.fori_loop(0, n_stages, tick, x)
        # after n_stages ticks the fully-processed activation is back on stage 0
        from ..models.transformer import _dense, _layer_norm
        x = _layer_norm(x, shared["final_ln"]["scale"], shared["final_ln"]["bias"])
        pooled = jnp.mean(x, axis=1).astype(jnp.float32)
        logits = _dense(pooled, shared["head"]["kernel"], shared["head"]["bias"])
        per_ex = -jnp.sum(y * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        # only stage 0 holds the real result; zero others and sum over pp
        loss = jnp.where(s == 0, jnp.mean(per_ex), 0.0)
        return jax.lax.psum(loss, pp_axis)

    param_specs = {"stages": P(pp_axis), "shared": P()}  # pytree prefixes

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P(), P(), P()),
             out_specs=(param_specs, P()),
             check_vma=False)
    def grad_fn(pp_params, ids, y, rng):
        if ids.shape[0] % n_microbatches or ids.shape[0] < n_microbatches:
            raise ValueError(
                f"batch {ids.shape[0]} must be a positive multiple of "
                f"n_microbatches={n_microbatches}")
        mb = ids.shape[0] // n_microbatches

        def micro(i, carry):
            grads_acc, loss_acc = carry
            sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * mb, mb, axis=0)
            loss, g = jax.value_and_grad(forward_one)(
                pp_params, sl(ids), sl(y), jax.random.fold_in(rng, i))
            grads_acc = jax.tree.map(jnp.add, grads_acc, g)
            return grads_acc, loss_acc + loss

        zero = jax.tree.map(jnp.zeros_like, pp_params)
        grads, loss = jax.lax.fori_loop(0, n_microbatches, micro,
                                        (zero, jnp.zeros(())))
        grads = jax.tree.map(lambda x: x / n_microbatches, grads)
        # shared params got gradient contributions on every stage: reduce;
        # stage params are exclusively local (their grads are already correct)
        grads["shared"] = jax.tree.map(
            lambda gg: jax.lax.psum(gg, pp_axis), grads["shared"])
        return grads, loss / n_microbatches

    def step(pp_params, opt_state, ids, y, rng):
        grads, loss = grad_fn(pp_params, ids, y, rng)
        # the optax update runs under GSPMD: sharded stage leaves update
        # locally, replicated shared leaves update identically everywhere
        updates, opt_state = optimizer.update(grads, opt_state, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
        return pp_params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
