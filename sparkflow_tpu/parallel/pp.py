"""Pipeline parallelism: transformer blocks sharded into stages over ``pp``.

Each device on the ``pp`` mesh axis holds 1/P of the transformer blocks
(stacked and sharded on a leading stage axis), so model memory scales down
with pipeline depth. Activations travel stage-to-stage with ``ppermute`` over
the ICI ring; microbatches bound activation memory and gradients accumulate
across them. Differentiation flows through the collective (ppermute transposes
to the reverse permute), so this is a complete train step, not a forward-only
demo.

Three schedules share the layout and numerics:

- ``'gpipe'`` (default): the overlapped fill-drain schedule. Every tick, ALL
  stages compute concurrently — stage ``s`` works on microbatch ``t - s`` —
  so a step's serial span is ``M + P - 1`` stage-times instead of the
  sequential ``M * P`` (utilization ``M/(M+P-1)``; Huang et al., GPipe).
  Invalid (fill/drain) ticks compute on placeholder activations whose chains
  never reach a live loss term, so masking them keeps gradients exact.
  Autodiff reverses the schedule tick-by-tick (ppermute transposes to the
  reverse ring), giving the overlapped backward for free; per-tick
  ``jax.checkpoint`` keeps activation memory at stage boundaries.
- ``'1f1b'``: one-forward-one-backward (PipeDream-flush / Megatron
  non-interleaved). The schedule is SIMULATED in numpy at trace time
  (P, M are static) into per-tick op tables; the compiled step is a single
  ``lax.scan`` whose tick does the table's op — a hand-scheduled backward
  via ``jax.vjp`` per microbatch with stage-input recompute, NOT autodiff
  of the whole schedule. Peak activation memory is **P microbatch inputs**
  per stage (the 1F1B bound) vs the fill-drain schedule's ``M + P - 1``
  saved boundary activations; serial span is ``~2M + 2P - 3`` combined
  fwd+bwd stage-times (GPipe's combined span is the same asymptotically —
  1F1B's win is memory, not bubble).
- ``'sequential'``: the round-1 schedule (one stage live per tick), kept as
  the numerics cross-check baseline.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def split_stage_params(model, params, n_stages: int):
    """Repack transformer params into the pipeline layout:

    - ``stages``: every per-block leaf stacked to [n_stages, blocks_per_stage, ...]
      (shard the leading axis over 'pp')
    - ``shared``: embed / final_ln / head, replicated on every stage.
    """
    n_layers = model.num_layers
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} blocks not divisible by {n_stages} stages")
    per = n_layers // n_stages
    blocks = [params[f"block_{i}"] for i in range(n_layers)]
    stage_trees = []
    for s in range(n_stages):
        group = blocks[s * per:(s + 1) * per]
        stage_trees.append(jax.tree.map(lambda *xs: jnp.stack(xs), *group))
    stages = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_trees)
    # copy shared leaves: the pp train step donates its params, and aliasing
    # the caller's arrays would delete them out from under the caller
    shared = jax.tree.map(jnp.array,
                          {k: v for k, v in params.items()
                           if not k.startswith("block_")})
    return {"stages": stages, "shared": shared}


def merge_stage_params(model, pp_params):
    """Inverse of :func:`split_stage_params` (e.g. for checkpoint export)."""
    n_layers = model.num_layers
    stages = pp_params["stages"]
    flat_example = jax.tree.leaves(stages)[0]
    n_stages, per = flat_example.shape[0], flat_example.shape[1]
    assert n_stages * per == n_layers
    out = dict(pp_params["shared"])
    for i in range(n_layers):
        s, b = divmod(i, per)
        out[f"block_{i}"] = jax.tree.map(lambda x: x[s, b], stages)
    return out


def pp_pspecs(pp_params):
    """PartitionSpecs: stage axis over 'pp', shared replicated."""
    stages = jax.tree.map(lambda x: P("pp"), pp_params["stages"])
    shared = jax.tree.map(lambda x: P(), pp_params["shared"])
    return {"stages": stages, "shared": shared}


def split_stage_pspecs(pp_axis: str, block_pspecs, shared_pspecs):
    """PartitionSpecs for the :func:`split_stage_params` layout that KEEP
    per-block leaf sharding: every stage leaf becomes
    ``P(pp_axis, None, *block_leaf_spec)`` — the leading stage axis shards
    over ``pp_axis``, the blocks-per-stage axis stays replicated, and the
    original per-block axes (e.g. megatron ``tp`` columns) ride behind. This
    is how the serving engine composes a 2D ``pp x tp`` mesh: depth shards
    via the stage stack, width via the block leaves. ``block_pspecs`` is the
    spec tree for ONE block; ``shared_pspecs`` passes through for the
    stage-replicated embed/final_ln leaves."""
    stages = jax.tree.map(lambda sp: P(pp_axis, None, *tuple(sp)),
                          block_pspecs,
                          is_leaf=lambda x: isinstance(x, P))
    return {"stages": stages, "shared": shared_pspecs}


_OP_NONE, _OP_FWD, _OP_BWD = 0, 1, 2


def _simulate_1f1b(P: int, M: int):
    """Tick-by-tick 1F1B schedule tables (pure python; P, M static).

    Greedy rule per stage: backward when a cotangent is ready and either the
    in-flight limit ``P - s`` is hit or no forward is possible; otherwise
    forward when an activation is ready. Yields the classic warmup /
    steady-1F1B / cooldown shape. Returns:

    - ``ops[t, s]``   — executed op (NONE/FWD/BWD); the LAST stage's FWD is
      rewritten to NONE (its input is already stored by the arrival write,
      and its BWD tick recomputes forward through the head anyway)
    - ``mbs[t, s]``   — microbatch index of the op
    - ``arrf[t, s]``  — 1 when a forward activation arrives at stage s this
      tick (stage s-1 ran FWD at t-1); ``arrm[t, s]`` its microbatch.

    Invariants (asserted): per-stage live-slot window never exceeds P and
    in-window microbatches stay distinct mod P — so one ``[P, ...]`` ring
    buffer keyed ``m % P`` is both the arrival queue and the bwd input store.
    Cotangents always arrive exactly on their consumption tick (bwd has
    priority), so they need no buffer at all.
    """
    ops, mbs = [], []
    fwd_done = [0] * P
    bwd_done = [0] * P
    act_ready = [dict() for _ in range(P)]
    cot_ready = [dict() for _ in range(P)]
    for m in range(M):
        act_ready[0][m] = 0
    t = 0
    while any(b < M for b in bwd_done):
        if t > 4 * (M + P) + 16:
            raise AssertionError("1f1b schedule failed to converge")
        row_op, row_mb = [_OP_NONE] * P, [0] * P
        for s in range(P):
            in_flight = fwd_done[s] - bwd_done[s]
            m_b, m_f = bwd_done[s], fwd_done[s]
            can_bwd = m_b < M and cot_ready[s].get(m_b, 1 << 30) <= t
            can_fwd = m_f < M and act_ready[s].get(m_f, 1 << 30) <= t
            if can_bwd and (in_flight >= P - s or not can_fwd):
                row_op[s], row_mb[s] = _OP_BWD, m_b
            elif can_fwd and in_flight < P - s:
                row_op[s], row_mb[s] = _OP_FWD, m_f
        for s in range(P):
            if row_op[s] == _OP_FWD:
                m = row_mb[s]
                fwd_done[s] += 1
                if s + 1 < P:
                    act_ready[s + 1][m] = t + 1
                else:
                    cot_ready[s][m] = t + 1
            elif row_op[s] == _OP_BWD:
                m = row_mb[s]
                bwd_done[s] += 1
                if s - 1 >= 0:
                    cot_ready[s - 1][m] = t + 1
        ops.append(row_op)
        mbs.append(row_mb)
        t += 1
    ops = np.array(ops, np.int32)
    mbs = np.array(mbs, np.int32)
    T = ops.shape[0]
    arrf = np.zeros((T, P), np.int32)
    arrm = np.zeros((T, P), np.int32)
    for tt in range(1, T):
        for s in range(1, P):
            if ops[tt - 1, s - 1] == _OP_FWD:
                arrf[tt, s] = 1
                arrm[tt, s] = mbs[tt - 1, s - 1]
    # check the ring-buffer invariants (see docstring)
    for s in range(1, P):
        live = set()
        for tt in range(T):
            if arrf[tt, s]:
                live.add(int(arrm[tt, s]))
            if ops[tt, s] == _OP_BWD:
                live.discard(int(mbs[tt, s]))
            if len(live) > 1:
                ms = sorted(live)
                assert len(live) <= P and ms[-1] - ms[0] < P, (s, tt, ms)
    # last stage executes nothing at its FWD ticks (timing only — see doc)
    ops_exec = ops.copy()
    ops_exec[:, P - 1] = np.where(ops_exec[:, P - 1] == _OP_FWD, _OP_NONE,
                                  ops_exec[:, P - 1])
    return ops_exec, mbs, arrf, arrm


def make_pp_train_step(model, optimizer, mesh: Mesh, n_microbatches: int = 1,
                       pp_axis: str = "pp", schedule: str = "gpipe",
                       dp_axis: str = "dp", task: str = "classifier",
                       _raw: bool = False):
    """Pipeline-parallel train step for the transformer families.

    Signature: ``step(pp_params, opt_state, ids, y, rng) ->
    (pp_params, opt_state, loss)`` — params in :func:`split_stage_params`
    layout sharded over 'pp'. ``task``:

    - ``'classifier'`` — ``y`` is one-hot labels [B, C]; mean-pool + CE head.
    - ``'lm'``        — causal next-token NLL; ``y`` is the attention mask
      [B, S] (token weights for the loss; blocks run causal).

    When the mesh ALSO has a ``dp_axis``, the batch shards over it and each
    data-parallel replica runs the pipeline on its shard (stage grads pmean
    over dp; composition of pp x dp). ``schedule`` is ``'gpipe'``
    (overlapped, ``M + P - 1`` serial stage-times) or ``'sequential'``
    (``M * P``, the numerics baseline). The returned callable exposes
    ``schedule_ticks``: the number of serial stage-computations in its
    forward sweep.
    """
    if schedule not in ("gpipe", "1f1b", "sequential"):
        raise ValueError(f"unknown pp schedule {schedule!r}")
    if schedule == "1f1b" and mesh.shape[pp_axis] < 2:
        # the last-stage arrival-store optimization leaves a 1-stage table
        # with no forward ops at all — a degenerate pipeline anyway
        raise ValueError("schedule='1f1b' needs a pp axis of size >= 2")
    if task not in ("classifier", "lm"):
        raise ValueError(f"unknown pp task {task!r}")
    has_dp = dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1
    causal = task == "lm"
    n_stages = mesh.shape[pp_axis]
    per = model.num_layers // n_stages
    M = n_microbatches
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def stage_apply(stage_blocks, x, rng):
        """Apply this device's ``per`` blocks (stacked leading axis)."""

        def body(carry, block):
            x, rng = carry
            x, rng = model._block(block, x, None, causal, True, rng)
            return (x, rng), None

        (x, rng), _ = jax.lax.scan(body, (x, rng), stage_blocks)
        return x

    from ..models.transformer import _dense, _layer_norm

    def embed_micro(shared, ids, m_idx, mb):
        """Embed microbatch ``m_idx`` (clamped: fill/drain ticks reuse a real
        slice, their chains are masked out of the loss)."""
        mi = jnp.clip(m_idx, 0, M - 1)
        idsm = jax.lax.dynamic_slice_in_dim(ids, mi * mb, mb, axis=0)
        x = jnp.take(shared["embed"]["tok"], idsm, axis=0)
        x = x + shared["embed"]["pos"][:ids.shape[1]][None, :, :]
        return model.cast(x)

    def _mb_slice(a, m_idx, mb):
        return jax.lax.dynamic_slice_in_dim(
            a, jnp.clip(m_idx, 0, M - 1) * mb, mb, axis=0)

    def head_loss(shared, x, ids, y, m_idx, mb):
        """Mean loss of microbatch ``m_idx`` from final-stage activations."""
        x = _layer_norm(x, shared["final_ln"]["scale"], shared["final_ln"]["bias"])
        if task == "lm":
            idsm = _mb_slice(ids, m_idx, mb).astype(jnp.int32)
            w = _mb_slice(y, m_idx, mb)[:, 1:].astype(jnp.float32)
            logits = jnp.matmul(x.astype(jnp.float32),
                                shared["embed"]["tok"].T.astype(jnp.float32))
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            nll = -jnp.take_along_axis(logp, idsm[:, 1:, None], axis=-1)[..., 0]
            per_ex = (jnp.sum(nll * w, axis=-1)
                      / jnp.maximum(jnp.sum(w, axis=-1), 1e-6))
            return jnp.mean(per_ex)
        ym = _mb_slice(y, m_idx, mb)
        pooled = jnp.mean(x, axis=1).astype(jnp.float32)
        logits = _dense(pooled, shared["head"]["kernel"], shared["head"]["bias"])
        # softmax_xent accepts one-hot [mb, C] or index [mb]/[mb, 1] labels
        # (the estimator's scalar labelCol path) — a raw ym*log_softmax sum
        # would silently broadcast index labels into a meaningless loss
        from ..models.base import softmax_xent
        return jnp.mean(softmax_xent(logits, ym))

    # ---- gpipe: every stage computes every tick, on microbatch (t - s) ----

    def gpipe_loss(pp_params, ids, y, rng):
        s = jax.lax.axis_index(pp_axis)
        shared = pp_params["shared"]
        my_blocks = jax.tree.map(lambda a: a[0], pp_params["stages"])
        ids = ids.astype(jnp.int32)
        b, seq = ids.shape
        mb = b // M
        T = M + n_stages - 1  # fill-drain span

        ckpt_stage = jax.checkpoint(stage_apply)

        def tick(carry, t):
            x_in, loss_acc = carry
            m_here = t - s  # logical microbatch this stage holds at tick t
            # stage 0 ingests a fresh microbatch; later stages use the ring
            inj = embed_micro(shared, ids, t, mb)
            inp = jnp.where(s == 0, inj, x_in)
            out = ckpt_stage(my_blocks, inp,
                             jax.random.fold_in(rng, t * n_stages + s))
            # the final stage finishes microbatch m_here this tick
            lval = head_loss(shared, out, ids, y, m_here, mb)
            live = (s == n_stages - 1) & (m_here >= 0) & (m_here < M)
            loss_acc = loss_acc + jnp.where(live, lval, 0.0)
            x_next = jax.lax.ppermute(out, pp_axis, ring)
            return (x_next, loss_acc), None

        x0 = jnp.zeros((mb, seq, model.hidden),
                       model.compute_dtype or jnp.float32)
        (_, loss_acc), _ = jax.lax.scan(tick, (x0, jnp.zeros(())),
                                        jnp.arange(T))
        # LOCAL contribution (nonzero on the last stage only). Deliberately
        # NOT psum'd here: differentiating through a psum inside shard_map
        # transposes it as psum — every device would receive the SUM of all
        # devices' cotangent seeds and grads would inflate by P. The caller
        # psums the forward value for reporting only.
        return loss_acc / M

    # ---- 1f1b: table-driven one-forward-one-backward (see module doc) -----

    if schedule == "1f1b":
        _ops_np, _mbs_np, _arrf_np, _arrm_np = _simulate_1f1b(n_stages, M)
        _T_1f1b = _ops_np.shape[0]
        ring_back = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def f1b_grads_and_loss(pp_params, ids, y, rng):
        """Hand-scheduled 1F1B step body (inside shard_map). Returns LOCAL
        (grads, loss_sum) — the caller does the pp/dp reductions."""
        s = jax.lax.axis_index(pp_axis)
        shared = pp_params["shared"]
        my_blocks = jax.tree.map(lambda a: a[0], pp_params["stages"])
        ids = ids.astype(jnp.int32)
        b, seq = ids.shape
        mb = b // M
        dt = model.compute_dtype or jnp.float32
        zeros_act = jnp.zeros((mb, seq, model.hidden), dt)
        zero_dgr = jax.tree.map(jnp.zeros_like, pp_params)
        OPS, MBS = jnp.asarray(_ops_np), jnp.asarray(_mbs_np)
        ARRF, ARRM = jnp.asarray(_arrf_np), jnp.asarray(_arrm_np)

        def _rng_for(m):
            # fwd send and bwd recompute fold identically -> same dropout
            return jax.random.fold_in(rng, m * n_stages + s)

        def tick(carry, t):
            xbuf, send_f, send_b, gacc, lacc = carry
            x_arr = jax.lax.ppermute(send_f, pp_axis, ring)
            g_arr = jax.lax.ppermute(send_b, pp_axis, ring_back)
            op = OPS[t][s]
            m = MBS[t][s]
            # arrival: stash the incoming activation in its ring slot (the
            # same buffer the bwd recompute reads — invariants in
            # _simulate_1f1b guarantee no live slot is ever clobbered)
            slot_in = ARRM[t][s] % n_stages
            xbuf = jax.lax.cond(
                ARRF[t][s] == 1,
                lambda xb: jax.lax.dynamic_update_index_in_dim(
                    xb, x_arr.astype(dt), slot_in, 0),
                lambda xb: xb, xbuf)

            def none_br(_):
                return zeros_act, zeros_act, zero_dgr, jnp.zeros(())

            def fwd_br(_):
                x0 = embed_micro(shared, ids, m, mb)
                xs = jax.lax.dynamic_index_in_dim(xbuf, m % n_stages, 0,
                                                  keepdims=False)
                x_in = jnp.where(s == 0, x0, xs)
                out = stage_apply(my_blocks, x_in, _rng_for(m))
                return out, zeros_act, zero_dgr, jnp.zeros(())

            def bwd_br(_):
                xs = jax.lax.dynamic_index_in_dim(xbuf, m % n_stages, 0,
                                                  keepdims=False)
                rngm = _rng_for(m)

                def last_br(_):
                    def f(blocks, sh, x):
                        return head_loss(sh, stage_apply(blocks, x, rngm),
                                         ids, y, m, mb)
                    lval, vjp = jax.vjp(f, my_blocks, shared, xs)
                    db, dsh, dx = vjp(jnp.ones(()))
                    return db, dsh, dx.astype(dt), lval

                def first_br(_):
                    def f(blocks, sh):
                        return stage_apply(
                            blocks, embed_micro(sh, ids, m, mb), rngm)
                    out, vjp = jax.vjp(f, my_blocks, shared)
                    db, dsh = vjp(g_arr.astype(out.dtype))
                    return db, dsh, zeros_act, jnp.zeros(())

                def mid_br(_):
                    def f(blocks, x):
                        return stage_apply(blocks, x, rngm)
                    out, vjp = jax.vjp(f, my_blocks, xs)
                    db, dx = vjp(g_arr.astype(out.dtype))
                    dsh = jax.tree.map(jnp.zeros_like, shared)
                    return db, dsh, dx.astype(dt), jnp.zeros(())

                db, dsh, dx, lval = jax.lax.cond(
                    s == n_stages - 1, last_br,
                    lambda o: jax.lax.cond(s == 0, first_br, mid_br, o),
                    None)
                dgr = {"stages": jax.tree.map(lambda g: g[None], db),
                       "shared": dsh}
                return zeros_act, dx, dgr, lval

            send_f_new, send_b_new, dgr, dl = jax.lax.switch(
                op, [none_br, fwd_br, bwd_br], None)
            gacc = jax.tree.map(jnp.add, gacc, dgr)
            return (xbuf, send_f_new, send_b_new, gacc, dl + lacc), None

        xbuf0 = jnp.zeros((n_stages, mb, seq, model.hidden), dt)
        carry0 = (xbuf0, zeros_act, zeros_act, zero_dgr, jnp.zeros(()))
        (_, _, _, grads, loss_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(_T_1f1b))
        grads = jax.tree.map(lambda g: g / M, grads)
        return grads, loss_sum / M

    # ---- sequential: one stage live per tick (round-1 baseline) -----------

    def forward_one(pp_params, ids, y, rng):
        s = jax.lax.axis_index(pp_axis)
        shared = pp_params["shared"]
        my_blocks = jax.tree.map(lambda a: a[0], pp_params["stages"])

        ids = ids.astype(jnp.int32)
        b, seq = ids.shape
        x = jnp.take(shared["embed"]["tok"], ids, axis=0)
        x = x + shared["embed"]["pos"][:seq][None, :, :]
        x = model.cast(x)

        def tick(t, x):
            def run(x):
                return stage_apply(my_blocks, x, jax.random.fold_in(rng, t))
            x = jax.lax.cond(s == t, run, lambda x: x, x)
            return jax.lax.ppermute(x, pp_axis, ring)

        x = jax.lax.fori_loop(0, n_stages, tick, x)
        # after n_stages ticks the fully-processed activation is back on
        # stage 0; head_loss (which applies the final layer norm) with
        # m_idx=0 and mb=rows reuses the task-specific head — the caller
        # already sliced this microbatch
        lval = head_loss(shared, x, ids, y, 0, ids.shape[0])
        # only stage 0 holds the real result: the LOCAL masked contribution
        # (no psum here — see gpipe_loss on why psum-in-the-loss inflates
        # gradients by P under shard_map autodiff)
        return jnp.where(s == 0, lval, 0.0)

    param_specs = {"stages": P(pp_axis), "shared": P()}  # pytree prefixes
    data_spec = P(dp_axis) if has_dp else P()

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, data_spec, data_spec, P()),
             out_specs=(param_specs, P()),
             check_vma=False)
    def grad_fn(pp_params, ids, y, rng):
        if ids.shape[0] % M or ids.shape[0] < M:
            raise ValueError(
                f"batch {ids.shape[0]} must be a positive multiple of "
                f"n_microbatches={M}")
        if has_dp:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(dp_axis))
        if schedule == "gpipe":
            loss, grads = jax.value_and_grad(gpipe_loss, argnums=0)(
                pp_params, ids, y, rng)
            loss = jax.lax.psum(loss, pp_axis)  # reporting only
        elif schedule == "1f1b":
            grads, loss = f1b_grads_and_loss(pp_params, ids, y, rng)
            loss = jax.lax.psum(loss, pp_axis)  # nonzero on last stage only
        else:
            # per-microbatch value_and_grad accumulation: only one
            # microbatch's activations are ever live during backward
            mb = ids.shape[0] // M

            def micro(i, carry):
                grads_acc, loss_acc = carry
                sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * mb, mb, 0)
                l, g = jax.value_and_grad(forward_one)(
                    pp_params, sl(ids), sl(y), jax.random.fold_in(rng, i))
                return jax.tree.map(jnp.add, grads_acc, g), loss_acc + l

            zero = jax.tree.map(jnp.zeros_like, pp_params)
            grads, loss = jax.lax.fori_loop(0, M, micro, (zero, jnp.zeros(())))
            grads = jax.tree.map(lambda x: x / M, grads)
            loss = jax.lax.psum(loss, pp_axis) / M  # reporting only
        # shared params got gradient contributions on every stage: reduce;
        # stage params are exclusively pp-local (grads already correct per
        # stage) but with data parallelism every dp replica contributed
        grads["shared"] = jax.tree.map(
            lambda gg: jax.lax.psum(gg, pp_axis), grads["shared"])
        if has_dp:
            grads = jax.tree.map(lambda gg: jax.lax.pmean(gg, dp_axis), grads)
            loss = jax.lax.pmean(loss, dp_axis)
        return grads, loss

    def step(pp_params, opt_state, ids, y, rng):
        grads, loss = grad_fn(pp_params, ids, y, rng)
        # the optax update runs under GSPMD: sharded stage leaves update
        # locally, replicated shared leaves update identically everywhere
        updates, opt_state = optimizer.update(grads, opt_state, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
        return pp_params, opt_state, loss

    # _raw hands back the traceable step for callers embedding it in their
    # own compiled program (the trainer's epoch scan); default is jitted.
    out = step if _raw else jax.jit(step, donate_argnums=(0, 1))
    # serial forward span in stage-times: the schedule's defining number
    # (for 1f1b the table length counts COMBINED fwd+bwd compute slots)
    out.schedule_ticks = (M + n_stages - 1 if schedule == "gpipe"
                          else _T_1f1b if schedule == "1f1b"
                          else M * n_stages)
    return out
