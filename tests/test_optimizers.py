"""All 10 named optimizers step and reduce loss on a convex problem."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sparkflow_tpu.graph_utils import (build_adam_config, build_adadelta_config,
                                       build_adagrad_config, build_ftrl_config,
                                       build_gradient_descent,
                                       build_momentum_config,
                                       build_rmsprop_config, generate_config)
from sparkflow_tpu.optimizers import (AVAILABLE_OPTIMIZERS, build_optimizer,
                                      build_optimizer_from_json)


def quad_loss(p):
    return jnp.sum(jnp.square(p["w"]["v"] - 3.0))


@pytest.mark.parametrize("name", AVAILABLE_OPTIMIZERS)
def test_optimizer_reduces_convex_loss(name):
    params = {"w": {"v": jnp.zeros((4,))}}
    opt = build_optimizer(name, learning_rate=0.1, optimizer_options=None)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(quad_loss)(params)
        upd, state = opt.update(g, state, params)
        return optax.apply_updates(params, upd), state, loss

    loss0 = float(quad_loss(params))
    for _ in range(60):
        params, state, loss = step(params, state)
    assert float(loss) < loss0


def test_unknown_name_falls_back_to_sgd():
    """Reference behavior: unknown names use gradient_descent
    (sparkflow/tensorflow_async.py:40-42)."""
    opt = build_optimizer("definitely_not_real", 0.5, None)
    params = {"w": {"v": jnp.array([1.0])}}
    upd, _ = opt.update({"w": {"v": jnp.array([1.0])}}, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["w"]["v"]), [-0.5])


def test_config_builders_round_trip():
    for cfg, name in [
        (build_adam_config(learning_rate=0.002, beta1=0.8), "adam"),
        (build_rmsprop_config(decay=0.95, centered=True), "rmsprop"),
        (build_momentum_config(momentum=0.5, use_nesterov=True), "momentum"),
        (build_adadelta_config(rho=0.9), "adadelta"),
        (build_adagrad_config(initial_accumulator=0.2), "adagrad"),
        (build_gradient_descent(learning_rate=0.3), "gradient_descent"),
        (build_ftrl_config(l1_regularization_strength=0.01), "ftrl"),
        (generate_config(learning_rate=0.1, use_locking=True), "proximal_adagrad"),
    ]:
        opt = build_optimizer_from_json(name, None, cfg)
        params = {"w": {"v": jnp.ones((2,))}}
        upd, _ = opt.update({"w": {"v": jnp.ones((2,))}}, opt.init(params), params)
        assert np.all(np.isfinite(np.asarray(upd["w"]["v"])))


def test_ftrl_l1_produces_sparsity():
    """FTRL with strong l1 should drive small-signal weights to exactly zero."""
    opt = build_optimizer("ftrl", 0.5, {"l1_regularization_strength": 2.0})
    params = {"w": {"v": jnp.array([0.0, 0.0])}}
    state = opt.init(params)
    g = {"w": {"v": jnp.array([0.01, -0.01])}}  # tiny gradients: l1 dominates
    for _ in range(5):
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
    np.testing.assert_allclose(np.asarray(params["w"]["v"]), [0.0, 0.0])


def test_momentum_default_when_no_options():
    """momentum defaults to 0.9 with no options (tensorflow_async.py:36-38):
    two identical-gradient steps must move farther than 2x a single step."""
    opt = build_optimizer("momentum", 1.0, None)
    params = {"w": {"v": jnp.array([0.0])}}
    state = opt.init(params)
    g = {"w": {"v": jnp.array([1.0])}}
    upd1, state = opt.update(g, state, params)
    params = optax.apply_updates(params, upd1)
    upd2, state = opt.update(g, state, params)
    # second update includes momentum: |upd2| = 1 + 0.9
    np.testing.assert_allclose(np.asarray(upd2["w"]["v"]), [-1.9], rtol=1e-6)
