"""Resilience layer: retry policy, fault injection, crash-consistent
checkpoints, bit-identical resume, serving drain, client retries."""

import os
import signal
import threading
import time
import urllib.error

import jax
import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.checkpoint import CheckpointError, CheckpointManager
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.resilience import (RetryExhausted, RetryPolicy, faults,
                                      run_resilient_fit)
from sparkflow_tpu.resilience.lifecycle import Lifecycle, ServerState
from sparkflow_tpu.trainer import Trainer


# -- retry policy (stubbed clock/sleep: no real waiting) ---------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, d):
        self.t += d


def test_retry_succeeds_after_transient_failures():
    clock = _Clock()
    pol = RetryPolicy(max_attempts=5, base_s=1.0, multiplier=2.0, max_s=100.0,
                      jitter=0.0, sleep=clock.sleep, clock=clock)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 4
    assert clock.t == pytest.approx(1.0 + 2.0 + 4.0)  # exponential, no jitter


def test_retry_exhausted_is_structured():
    clock = _Clock()
    pol = RetryPolicy(max_attempts=3, base_s=0.5, jitter=0.0,
                      sleep=clock.sleep, clock=clock)

    def always():
        raise ValueError("boom")

    with pytest.raises(RetryExhausted) as ei:
        pol.call(always, describe="doomed op")
    e = ei.value
    assert e.op == "doomed op" and e.attempts == 3
    assert isinstance(e.last_error, ValueError)
    assert isinstance(e.__cause__, ValueError)
    assert "doomed op" in str(e) and "boom" in str(e)


def test_retry_deadline_cuts_attempts_short():
    clock = _Clock()
    pol = RetryPolicy(max_attempts=100, base_s=10.0, max_s=100.0, jitter=0.0,
                      deadline_s=5.0, sleep=clock.sleep, clock=clock)
    with pytest.raises(RetryExhausted) as ei:
        pol.call(lambda: (_ for _ in ()).throw(OSError("x")))
    assert ei.value.attempts == 1  # first backoff (10s) would bust 5s budget


def test_retry_non_retryable_propagates_untouched():
    pol = RetryPolicy(max_attempts=5, retry_on=(OSError,),
                      sleep=lambda d: None)
    with pytest.raises(KeyError):
        pol.call(lambda: (_ for _ in ()).throw(KeyError("nope")))


def test_retry_jitter_is_seeded_and_bounded():
    a = [RetryPolicy(base_s=1.0, jitter=0.5, seed=7).backoff(0)
         for _ in range(3)]
    b = [RetryPolicy(base_s=1.0, jitter=0.5, seed=7).backoff(0)
         for _ in range(3)]
    assert a == b  # reproducible
    for d in a:
        assert 0.5 <= d <= 1.5


# -- fault points ------------------------------------------------------------

def test_fire_is_noop_when_unarmed():
    faults.fire("nonexistent.point")  # must not raise


def test_inject_fails_chosen_calls_and_counts():
    with faults.inject("p.x", fail_calls=[1]) as spec:
        faults.fire("p.x")
        with pytest.raises(faults.InjectedFault):
            faults.fire("p.x")
        faults.fire("p.x")
        assert spec.calls == 3 and spec.failures == 1
    faults.fire("p.x")  # disarmed on exit


def test_inject_max_failures_lets_retries_win():
    with faults.inject("p.y", p_fail=1.0, max_failures=2) as spec:
        pol = RetryPolicy(max_attempts=5, base_s=0.0, jitter=0.0,
                          sleep=lambda d: None)
        pol.call(lambda: faults.fire("p.y"))
        assert spec.failures == 2 and spec.calls == 3


def test_inject_refuses_double_arming():
    with faults.inject("p.z"):
        with pytest.raises(RuntimeError):
            with faults.inject("p.z"):
                pass


# -- crash-consistent checkpoints -------------------------------------------

def _state(v=0.0):
    return {"params": {"w": np.full((4, 3), v, np.float32)},
            "step": np.int64(1)}


def test_save_is_atomic_under_pre_commit_crash(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    with faults.inject("checkpoint.pre_commit", fail_calls=[0]):
        with pytest.raises(faults.InjectedFault):
            mgr.save(2, _state(2.0))
    # the torn save left no step dir, no tmp litter, and a usable step 1
    assert mgr.all_steps() == [1]
    assert not [n for n in os.listdir(tmp_path) if n.startswith("_tmp")]
    assert mgr.latest_step() == 1
    r = mgr.restore()
    assert np.all(np.asarray(r["params"]["w"]) == 1.0)


def test_latest_json_garbled_falls_back_to_scan(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state())
    mgr.save(2, _state())
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="latest_json")
    assert mgr.latest_step() == 2
    # missing entirely is also fine
    os.remove(tmp_path / "latest.json")
    assert mgr.latest_step() == 2


def test_manifest_catches_corruption_and_restore_falls_back(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    step, _path = faults.corrupt_latest_checkpoint(str(tmp_path), mode="flip")
    assert step == 2
    assert mgr.verify_step(2) is False and mgr.verify_step(1) is True
    r = mgr.restore()  # falls back past the corrupt step automatically
    assert np.all(np.asarray(r["params"]["w"]) == 1.0)


def test_truncation_and_manifest_garbling_detected(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="truncate")
    assert mgr.verify_step(2) is False
    mgr.save(3, _state(3.0))
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="manifest")
    assert mgr.verify_step(3) is False
    r = mgr.restore()
    assert np.all(np.asarray(r["params"]["w"]) == 1.0)


def test_all_corrupt_raises_checkpoint_error(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state())
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="flip")
    with pytest.raises(CheckpointError):
        mgr.restore()


def test_explicit_step_never_falls_back(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="flip")
    with pytest.raises(CheckpointError):
        mgr.restore(step=2)
    r = mgr.restore(step=1)
    assert np.all(np.asarray(r["params"]["w"]) == 1.0)


def test_legacy_dir_without_manifest_is_accepted(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _state(5.0))
    os.remove(tmp_path / "step_1" / "manifest.json")
    assert mgr.verify_step(1) is None  # unverifiable, not invalid
    r = mgr.restore()
    assert np.all(np.asarray(r["params"]["w"]) == 5.0)


def test_empty_directory_restores_none(tmp_path):
    assert CheckpointManager(str(tmp_path)).restore() is None


# -- bit-identical resume ----------------------------------------------------

def _reg_graph():
    x = nn.placeholder([None, 6], name="x")
    y = nn.placeholder([None, 1], name="y")
    h = nn.dense(x, 8, activation="relu")
    o = nn.dense(h, 1, name="out")
    nn.mean_squared_error(y, o)


@pytest.fixture(scope="module")
def reg_data():
    rs = np.random.RandomState(0)
    X = rs.randn(97, 6).astype(np.float32)
    Y = (X @ rs.randn(6))[:, None].astype(np.float32)
    return X, Y


def _trainer(ckdir, cb=None, retries=0):
    return Trainer(build_graph(_reg_graph), "x:0", "y:0", iters=8,
                   mini_batch_size=32, checkpoint_dir=ckdir,
                   checkpoint_every=2, seed=3, loss_callback=cb,
                   resume_retries=retries)


def _leaves(params):
    return np.concatenate([np.ravel(np.asarray(l))
                           for l in jax.tree.leaves(params)])


@pytest.fixture(scope="module")
def baseline(reg_data, tmp_path_factory):
    X, Y = reg_data
    d = tmp_path_factory.mktemp("base")
    # loss_callback keeps the loop path so trajectories match injected runs
    return _trainer(str(d), cb=lambda *a: None).fit(X, Y)


def test_crash_then_resilient_fit_is_bit_identical(reg_data, baseline,
                                                   tmp_path):
    X, Y = reg_data
    crash = faults.crash_at(5)  # epoch 5 raises once; latest checkpoint is 4
    res = run_resilient_fit(_trainer(str(tmp_path), cb=crash), X, Y,
                            max_restarts=2)
    assert crash.fired == 1
    assert res.stop_reason == "completed" and res.completed
    # same rng stream + optimizer state across the restart: exact equality
    assert np.array_equal(_leaves(baseline.params), _leaves(res.params))
    assert res.losses == baseline.losses[-len(res.losses):]


def test_in_fit_retry_budget_is_bit_identical(reg_data, baseline, tmp_path):
    X, Y = reg_data
    crash = faults.crash_at(5)
    res = _trainer(str(tmp_path), cb=crash, retries=2).fit(X, Y)
    assert crash.fired == 1 and res.completed
    assert np.array_equal(_leaves(baseline.params), _leaves(res.params))


def test_sigterm_preempts_then_resumes_bit_identical(reg_data, baseline,
                                                     tmp_path):
    X, Y = reg_data
    tr = _trainer(str(tmp_path), cb=faults.sigterm_at(3))
    first = tr.fit(X, Y)
    assert first.stop_reason == "preempted" and not first.completed
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.latest_step() == 3  # saved at the preemption point
    second = tr.fit(X, Y)  # injector is spent (times=1): runs to the end
    assert second.completed
    assert np.array_equal(_leaves(baseline.params), _leaves(second.params))


def test_resume_survives_corrupted_latest_checkpoint(reg_data, baseline,
                                                     tmp_path):
    X, Y = reg_data
    tr = _trainer(str(tmp_path), cb=faults.sigterm_at(5))
    tr.fit(X, Y)  # preempted at 5; checkpoints 2, 4, 5 on disk
    faults.corrupt_latest_checkpoint(str(tmp_path), mode="flip")
    # restore skips the torn step 5, resumes from 4, re-runs 5..8 — and the
    # deterministic trajectory still lands on the exact baseline weights
    res = tr.fit(X, Y)
    assert res.completed
    assert np.array_equal(_leaves(baseline.params), _leaves(res.params))


def test_driver_refuses_without_checkpoint_dir(reg_data):
    X, Y = reg_data
    tr = Trainer(build_graph(_reg_graph), "x:0", "y:0", iters=2,
                 mini_batch_size=32)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        run_resilient_fit(tr, X, Y)


def test_driver_exhausts_restart_budget(reg_data, tmp_path):
    X, Y = reg_data
    forever = faults.crash_at(3, times=99)  # re-fires on every resume
    pol = RetryPolicy(max_attempts=2, base_s=0.0, jitter=0.0, seed=0,
                      sleep=lambda d: None)
    with pytest.raises(RetryExhausted) as ei:
        run_resilient_fit(_trainer(str(tmp_path), cb=forever), X, Y,
                          max_restarts=1, restart_policy=pol)
    assert isinstance(ei.value.last_error, faults.InjectedFault)


# -- serving lifecycle -------------------------------------------------------

def test_lifecycle_edges_and_inflight():
    lc = Lifecycle()
    assert lc.state is ServerState.STARTING
    assert not lc.try_begin_request()  # not serving yet
    assert lc.transition(ServerState.SERVING)
    assert lc.try_begin_request() and lc.inflight == 1
    assert lc.transition(ServerState.DRAINING)
    assert not lc.try_begin_request()  # draining admits nothing
    assert not lc.transition(ServerState.SERVING)  # no un-drain edge
    assert not lc.transition(ServerState.DRAINING)  # repeat is a no-op
    assert not lc.wait_idle(timeout=0.05)  # one request still in flight
    lc.end_request()
    assert lc.wait_idle(timeout=1.0) and lc.inflight == 0
    assert lc.transition(ServerState.STOPPED)
    assert not lc.transition(ServerState.SERVING)


@pytest.fixture(scope="module")
def serving_engine():
    from sparkflow_tpu.serving import InferenceEngine

    def g():
        x = nn.placeholder([None, 4], name="x")
        nn.dense(x, 2, name="out")

    rs = np.random.RandomState(0)
    w = [rs.randn(4, 2).astype(np.float32), rs.randn(2).astype(np.float32)]
    return InferenceEngine(build_graph(g), w, input_name="x:0",
                           output_name="out/BiasAdd:0", max_batch=8)


def test_drain_finishes_inflight_and_sheds_new(serving_engine):
    from sparkflow_tpu.serving import (InferenceServer, ServingClient,
                                       ServingError)
    srv = InferenceServer(serving_engine, max_delay_ms=0.0).start()
    try:
        cli = ServingClient(srv.url, retries=0)
        assert cli.healthz()["state"] == "serving"
        with faults.inject("engine.predict", delay_ms=300):
            got = {}

            def slow():
                got["out"] = cli.predict(np.zeros((2, 4)).tolist())

            t = threading.Thread(target=slow)
            t.start()
            time.sleep(0.1)  # let it into the batcher
            dr = threading.Thread(target=srv.drain)
            dr.start()
            time.sleep(0.1)
            with pytest.raises(ServingError) as ei:
                ServingClient(srv.url, retries=0).predict(
                    np.zeros((1, 4)).tolist())
            assert ei.value.status == 503 and ei.value.code == "draining"
            assert ei.value.retry_after is not None  # Retry-After honored
            t.join(timeout=5)
            dr.join(timeout=5)
            assert got["out"].shape == (2, 2)  # in-flight request completed
        assert srv.lifecycle.state is ServerState.DRAINING
        with pytest.raises(ServingError) as ei:
            cli.healthz()  # readiness flips so balancers eject the replica
        assert ei.value.status == 503
    finally:
        srv.stop()
    assert srv.lifecycle.state is ServerState.STOPPED


def test_sigterm_triggers_graceful_drain(serving_engine):
    from sparkflow_tpu.serving import InferenceServer, ServingClient
    prev = signal.getsignal(signal.SIGTERM)
    srv = InferenceServer(serving_engine).start()
    try:
        assert srv.install_signal_handlers()
        cli = ServingClient(srv.url, retries=0)
        assert cli.predict(np.zeros((1, 4)).tolist()).shape == (1, 2)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while (srv.lifecycle.state is ServerState.SERVING
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert srv.lifecycle.state is ServerState.DRAINING
    finally:
        srv.stop()
    # stop() restored the previous SIGTERM disposition
    assert signal.getsignal(signal.SIGTERM) is prev


def test_batcher_drain_unit(serving_engine):
    from sparkflow_tpu.serving import Draining, MicroBatcher
    b = MicroBatcher(serving_engine, max_delay_ms=0.0, max_queue=64)
    try:
        fut = b.submit(np.zeros((2, 4), np.float32))
        assert fut.result(timeout=5).shape == (2, 2)
        b.begin_drain()
        with pytest.raises(Draining):
            b.submit(np.zeros((1, 4), np.float32))
        assert b.wait_drained(timeout=5)
    finally:
        b.close()


# -- serving client retries (stubbed transport: no sockets, no sleeping) -----

def _stub_policy(sleeps):
    return RetryPolicy(max_attempts=10, base_s=0.1, multiplier=2.0,
                       jitter=0.0, seed=0, sleep=sleeps.append)


def test_client_retries_503_until_success(monkeypatch):
    from sparkflow_tpu.serving.client import ServingClient, ServingError
    calls = {"n": 0}

    def fake(self, path, payload=None, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ServingError(503, "queue_full", "busy")
        return {"predictions": [[1.0, 2.0]]}

    monkeypatch.setattr(ServingClient, "_request", fake)
    sleeps = []
    c = ServingClient("http://stub", retries=3,
                      retry_policy=_stub_policy(sleeps))
    out = c.predict([[0.0]])
    assert out.shape == (1, 2) and calls["n"] == 3
    assert sleeps == [0.1, 0.2]  # exponential, jitter off


def test_client_honors_retry_after_hint(monkeypatch):
    from sparkflow_tpu.serving.client import ServingClient, ServingError
    calls = {"n": 0}

    def fake(self, path, payload=None, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ServingError(503, "draining", "drain", retry_after=2.5)
        return {"predictions": [[0.0]]}

    monkeypatch.setattr(ServingClient, "_request", fake)
    sleeps = []
    c = ServingClient("http://stub", retries=2,
                      retry_policy=_stub_policy(sleeps))
    c.predict([[0.0]])
    assert sleeps == [2.5]  # server hint overrides the smaller backoff


def test_client_retries_connection_errors(monkeypatch):
    from sparkflow_tpu.serving.client import ServingClient
    calls = {"n": 0}

    def fake(self, path, payload=None, **kw):
        calls["n"] += 1
        raise urllib.error.URLError(ConnectionRefusedError(111, "refused"))

    monkeypatch.setattr(ServingClient, "_request", fake)
    c = ServingClient("http://stub", retries=2,
                      retry_policy=_stub_policy([]))
    with pytest.raises(urllib.error.URLError):
        c.predict([[0.0]])
    assert calls["n"] == 3  # initial + 2 retries, then the error surfaces


def test_client_retries_zero_opts_out_and_4xx_never_retries(monkeypatch):
    from sparkflow_tpu.serving.client import ServingClient, ServingError
    calls = {"n": 0}

    def fake(self, path, payload=None, **kw):
        calls["n"] += 1
        raise ServingError(503 if calls["n"] == 1 else 400, "x", "y")

    monkeypatch.setattr(ServingClient, "_request", fake)
    c = ServingClient("http://stub", retries=0)
    with pytest.raises(ServingError):
        c.predict([[0.0]])
    assert calls["n"] == 1  # retries=0: fail fast
    calls["n"] = 1  # next call raises 400
    c2 = ServingClient("http://stub", retries=5,
                       retry_policy=_stub_policy([]))
    with pytest.raises(ServingError) as ei:
        c2.predict([[0.0]])
    assert ei.value.status == 400 and calls["n"] == 2  # no retry on 4xx


def test_client_deadline_raises_retry_exhausted(monkeypatch):
    from sparkflow_tpu.serving.client import ServingClient, ServingError
    monkeypatch.setattr(
        ServingClient, "_request",
        lambda self, path, payload=None, **kw: (_ for _ in ()).throw(
            ServingError(503, "queue_full", "busy")))
    pol = RetryPolicy(max_attempts=10, base_s=1.0, jitter=0.0,
                      deadline_s=0.5, sleep=lambda d: None)
    c = ServingClient("http://stub", retry_policy=pol)
    with pytest.raises(RetryExhausted):
        c.predict([[0.0]])


# -- coordinator join retry --------------------------------------------------

def test_initialize_retries_join_until_success(monkeypatch):
    from sparkflow_tpu.parallel import distributed as dist
    calls = []

    def fake_init(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    pol = RetryPolicy(max_attempts=5, base_s=0.0, jitter=0.0,
                      sleep=lambda d: None)
    dist.initialize(coordinator_address="10.0.0.1:8476", num_processes=1,
                    process_id=0, timeout_s=7, retry_policy=pol)
    assert len(calls) == 3 and dist._INITIALIZED
    assert calls[0]["initialization_timeout"] == 7


def test_initialize_retry_exhaustion_names_coordinator(monkeypatch):
    from sparkflow_tpu.parallel import distributed as dist

    def fake_init(**kw):
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    pol = RetryPolicy(max_attempts=2, base_s=0.0, jitter=0.0,
                      sleep=lambda d: None)
    with pytest.raises(RetryExhausted) as ei:
        dist.initialize(coordinator_address="10.0.0.9:1234", num_processes=2,
                        process_id=0, retry_policy=pol)
    assert "10.0.0.9:1234" in str(ei.value)
    assert not dist._INITIALIZED


def test_initialize_single_attempt_keeps_original_error(monkeypatch):
    from sparkflow_tpu.parallel import distributed as dist

    def fake_init(**kw):
        raise RuntimeError("boom")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    with pytest.raises(RuntimeError, match="boom"):  # not RetryExhausted
        dist.initialize(coordinator_address="10.0.0.1:8476", num_processes=1,
                        process_id=0)


def test_initialize_env_vars_drive_timeout_and_retries(monkeypatch):
    from sparkflow_tpu.parallel import distributed as dist
    calls = []

    def fake_init(**kw):
        calls.append(kw)
        if len(calls) < 2:
            raise RuntimeError("not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    monkeypatch.setenv("SPARKFLOW_TPU_COORD_TIMEOUT_S", "11")
    monkeypatch.setenv("SPARKFLOW_TPU_COORD_RETRIES", "3")
    # env-driven retries build the default policy (base 1s); one transient
    # failure costs a single jittered backoff, so the test stays fast
    dist.initialize(coordinator_address="10.0.0.1:8476",
                    num_processes=1, process_id=0)
    assert len(calls) == 2
    assert calls[0]["initialization_timeout"] == 11
    assert dist._INITIALIZED
