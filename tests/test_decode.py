"""Autoregressive decode serving: paged KV cache, paged-attention kernel,
DecodeEngine, continuous batching, and the /v1/generate HTTP front.

Covers the PR's acceptance criteria directly: pallas paged_attention parity
with the pure-JAX reference across page sizes and ragged lengths, page-pool
alloc/append/free/fragmentation invariants, continuous-batching join/retire
under mixed lengths with exact greedy parity against the full forward pass,
zero steady-state retraces (RecompileGuard gate), drain-under-load, and a
lock-lint (GC-L301/302/303) clean gate over the new serving files.
"""

import os
import threading
import time
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from sparkflow_tpu.analysis import jaxpr_lint, locks
from sparkflow_tpu.jax_compat import shard_map
from sparkflow_tpu.models import presets
from sparkflow_tpu.models.registry import build_registry_spec, model_from_json
from sparkflow_tpu.parallel.mesh import make_mesh
from sparkflow_tpu.sharding import ShardingConfig
from sparkflow_tpu.ops import (paged_attention, paged_attention_reference,
                               paged_attention_verify,
                               paged_attention_verify_reference)
from sparkflow_tpu.ops.attention import last_attention_path
from sparkflow_tpu.serving import (ContinuousBatcher, DecodeEngine, Draining,
                                   InferenceEngine, InferenceServer,
                                   OutOfPages, PagedKVCache, QueueFull,
                                   ServingClient, ServingError)
from sparkflow_tpu.utils.metrics import Metrics


# -- paged attention kernel ---------------------------------------------------


def _rand_paged(rs, b, h, d, page_size, max_pages, lengths):
    """Random q + pools + a valid page table for the given ragged lengths."""
    num_pages = 1 + b * max_pages  # page 0 is scratch
    q = rs.randn(b, h, d).astype(np.float32)
    k = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    v = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    table = np.zeros((b, max_pages), np.int32)
    nxt = 1
    for i, ln in enumerate(lengths):
        for p in range((ln + page_size - 1) // page_size):
            table[i, p] = nxt
            nxt += 1
    return q, k, v, table, np.asarray(lengths, np.int32)


@pytest.mark.parametrize("page_size", [8, 16, 64])
def test_paged_attention_parity_ragged(page_size):
    rs = np.random.RandomState(page_size)
    b, h, d, max_pages = 4, 4, 16, 3
    # ragged: empty slot, single token, mid-page, and a full table
    lengths = [0, 1, page_size + 3, max_pages * page_size]
    q, k, v, table, lens = _rand_paged(rs, b, h, d, page_size, max_pages,
                                       lengths)
    ref = paged_attention_reference(q, k, v, table, lens)
    out = paged_attention(q, k, v, table, lens, interpret=True)
    assert last_attention_path() == "pallas"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the empty slot must come out exactly zero, not NaN
    assert np.all(np.asarray(out)[0] == 0.0)
    assert np.isfinite(np.asarray(out)).all()


def test_paged_attention_matches_dense_softmax():
    """The reference itself checked against a from-scratch dense attention
    over the gathered pages (independent derivation, not a copy)."""
    rs = np.random.RandomState(7)
    b, h, d, page_size, max_pages = 2, 2, 8, 8, 2
    lengths = [5, 11]
    q, k, v, table, lens = _rand_paged(rs, b, h, d, page_size, max_pages,
                                       lengths)
    ref = np.asarray(paged_attention_reference(q, k, v, table, lens))
    for i, ln in enumerate(lengths):
        kk = k[table[i]].reshape(-1, h, d)[:ln]  # [ln, h, d]
        vv = v[table[i]].reshape(-1, h, d)[:ln]
        s = np.einsum("hd,lhd->hl", q[i], kk) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        o = np.einsum("hl,lhd->hd", p, vv)
        np.testing.assert_allclose(ref[i], o, atol=1e-5, rtol=1e-5)


def test_paged_attention_ignores_garbage_beyond_length():
    """Tokens past ``lengths`` (stale page remainder) must not leak in."""
    rs = np.random.RandomState(3)
    q, k, v, table, lens = _rand_paged(rs, 1, 2, 8, 8, 2, [9])
    out1 = np.asarray(paged_attention(q, k, v, table, lens, interpret=True))
    k2, v2 = k.copy(), v.copy()
    k2[table[0, 1], 2:] = 99.0  # beyond token 9 inside the second page
    v2[table[0, 1], 2:] = -99.0
    out2 = np.asarray(paged_attention(q, k2, v2, table, lens,
                                      interpret=True))
    np.testing.assert_allclose(out1, out2, atol=1e-6)


def test_paged_attention_aliased_pages_share_prefix():
    """Two slots whose tables alias the same physical page (shared prefix)
    must attend identically when their suffixes also match — the kernel is
    oblivious to sharing, only the table differs."""
    rs = np.random.RandomState(11)
    h, d, page_size = 2, 8, 8
    q1 = rs.randn(h, d).astype(np.float32)
    q = np.stack([q1, q1])  # same query for both slots
    k = rs.randn(4, page_size, h, d).astype(np.float32)
    v = rs.randn(4, page_size, h, d).astype(np.float32)
    k[3], v[3] = k[2], v[2]  # slot 1's private page duplicates slot 0's
    table = np.asarray([[1, 2], [1, 3]], np.int32)  # page 1 aliased
    lens = np.asarray([12, 12], np.int32)
    out = np.asarray(paged_attention(q, k, v, table, lens, interpret=True))
    np.testing.assert_allclose(out[0], out[1], atol=1e-6)


# -- multi-query verify kernel ------------------------------------------------


def _rand_paged_verify(rs, b, h, s, d, page_size, max_pages, starts):
    """Random multi-query chunk + pools + tables: slot i's chunk begins at
    absolute position ``starts[i]``, so its pages must cover
    ``starts[i] + s`` tokens."""
    num_pages = 1 + b * max_pages
    q = rs.randn(b, h, s, d).astype(np.float32)
    k = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    v = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    table = np.zeros((b, max_pages), np.int32)
    nxt = 1
    for i, st in enumerate(starts):
        for p in range((st + s + page_size - 1) // page_size):
            table[i, p] = nxt
            nxt += 1
    return q, k, v, table, np.asarray(starts, np.int32)


@pytest.mark.parametrize("page_size", [4, 8])
def test_paged_verify_parity_ragged_starts(page_size):
    """Pallas verify kernel == jnp reference across ragged chunk starts,
    including a chunk at position 0 (no committed history at all)."""
    rs = np.random.RandomState(page_size)
    b, h, s, d, max_pages = 4, 4, 4, 16, 4
    starts = [0, 1, page_size - 1, 2 * page_size + 3]
    q, k, v, table, st = _rand_paged_verify(rs, b, h, s, d, page_size,
                                            max_pages, starts)
    ref = paged_attention_verify_reference(q, k, v, table, st)
    out = paged_attention_verify(q, k, v, table, st, interpret=True)
    assert last_attention_path() == "pallas"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert np.isfinite(np.asarray(out)).all()


def test_paged_verify_reference_matches_dense_softmax():
    """The verify reference checked against a from-scratch per-query causal
    dense attention over the gathered pages (independent derivation)."""
    rs = np.random.RandomState(5)
    b, h, s, d, page_size, max_pages = 2, 2, 3, 8, 4, 4
    starts = [2, 6]
    q, k, v, table, st = _rand_paged_verify(rs, b, h, s, d, page_size,
                                            max_pages, starts)
    ref = np.asarray(paged_attention_verify_reference(q, k, v, table, st))
    for i in range(b):
        hist = k[table[i]].reshape(-1, h, d)
        vv = v[table[i]].reshape(-1, h, d)
        for j in range(s):
            ln = starts[i] + j + 1          # query j sees positions <= its own
            sc = np.einsum("hd,lhd->hl", q[i, :, j], hist[:ln]) / np.sqrt(d)
            p = np.exp(sc - sc.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            o = np.einsum("hl,lhd->hd", p, vv[:ln])
            np.testing.assert_allclose(ref[i, :, j], o, atol=1e-5, rtol=1e-5)


def test_paged_verify_s1_matches_single_query_kernel():
    """A one-position chunk is exactly the single-token decode attention:
    verify(S=1, start=L) == paged_attention(lengths=L+1)."""
    rs = np.random.RandomState(9)
    b, h, d, page_size, max_pages = 3, 4, 16, 8, 3
    lengths = [1, 9, 17]                    # committed history + the query
    q1, k, v, table, lens = _rand_paged(rs, b, h, d, page_size, max_pages,
                                        lengths)
    single = np.asarray(paged_attention(q1, k, v, table, lens,
                                        interpret=True))
    multi = np.asarray(paged_attention_verify(
        q1[:, :, None, :], k, v, table, lens - 1, interpret=True))
    np.testing.assert_allclose(multi[:, :, 0], single, atol=2e-5, rtol=2e-5)


def test_paged_verify_ignores_garbage_beyond_chunk():
    """K/V past the chunk's last position (stale page remainder — exactly
    what a rejected speculative suffix leaves behind) must not leak into any
    query's output."""
    rs = np.random.RandomState(13)
    b, h, s, d, page_size, max_pages = 1, 2, 3, 8, 8, 2
    q, k, v, table, st = _rand_paged_verify(rs, b, h, s, d, page_size,
                                            max_pages, [7])
    out1 = np.asarray(paged_attention_verify(q, k, v, table, st,
                                             interpret=True))
    k2, v2 = k.copy(), v.copy()
    k2[table[0, 1], 2:] = 77.0              # positions >= 10 > 7 + 3 - 1
    v2[table[0, 1], 2:] = -77.0
    out2 = np.asarray(paged_attention_verify(q, k2, v2, table, st,
                                             interpret=True))
    np.testing.assert_allclose(out1, out2, atol=1e-6)


# -- page pool ---------------------------------------------------------------


def test_kvcache_alloc_append_free_invariants():
    m = Metrics()
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=3,
                      max_pages_per_slot=4, metrics=m)
    assert kv.stats()["pages_total"] == 8
    # worst case 7 tokens = 2 pages; prompt 5 tokens allocates 2, reserves 0
    kv.alloc(0, prompt_tokens=5, total_tokens=7)
    st = kv.stats()
    assert st["pages_used"] == 2 and st["tokens"] == 5
    # internal fragmentation: 5 tokens in 2*4 slots -> 3/8 empty
    assert st["fragmentation"] == pytest.approx(1 - 5 / 8)
    # table entries are real pages; the padding stays on scratch page 0
    t = kv.page_tables()
    assert (t[0, :2] > 0).all() and (t[0, 2:] == 0).all()
    # appends inside the reservation never raise; page 3 appears at token 9
    kv.append(0, 3)  # 5 -> 8 tokens, still 2 pages
    assert kv.stats()["pages_used"] == 2
    with pytest.raises(OutOfPages):
        kv.append(0)  # 9th token needs a page beyond the reservation
    # a second sequence whose reservation doesn't fit is rejected up front
    kv.alloc(1, prompt_tokens=1, total_tokens=16)  # reserves all 4 pages
    with pytest.raises(OutOfPages):
        kv.alloc(2, prompt_tokens=1, total_tokens=12)
    assert m.summary()["counters"]["serving/kv/alloc_rejections"] == 1
    # 2 un-reserved pages remain: 1-page admits still fit, 3-page ones don't
    assert kv.can_admit(4)
    assert not kv.can_admit(12)
    # freeing returns held AND reserved pages; free is idempotent
    kv.free(1)
    kv.free(1)
    assert kv.can_admit(12)
    kv.free(0)
    st = kv.stats()
    assert st["pages_used"] == 0 and st["pages_free"] == 8
    assert st["slots_active"] == 0 and st["fragmentation"] == 0.0
    g = m.summary()["gauges"]
    assert g["serving/kv/occupancy"] == 0.0
    assert g["serving/kv/pages_used"] == 0


def test_kvcache_no_page_leak_under_churn():
    kv = PagedKVCache(num_pages=17, page_size=4, num_slots=4,
                      max_pages_per_slot=4)
    rs = np.random.RandomState(0)
    live = {}
    for it in range(200):
        slot = kv.free_slot()
        if slot is not None and rs.rand() < 0.6:
            total = int(rs.randint(1, 17))
            prompt = int(rs.randint(1, total + 1))
            if kv.can_admit(total):
                kv.alloc(slot, prompt, total)
                live[slot] = (kv.length(slot), total)
        for s in list(live):
            ln, total = live[s]
            if ln < total and rs.rand() < 0.7:
                kv.append(s)
                live[s] = (ln + 1, total)
            elif rs.rand() < 0.3:
                kv.free(s)
                del live[s]
    for s in list(live):
        kv.free(s)
    st = kv.stats()
    assert st["pages_free"] == 16 and st["pages_used"] == 0
    assert st["pages_reserved"] == 0 and st["tokens"] == 0


def test_kvcache_rejects_oversized_and_bad_slots():
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=2,
                      max_pages_per_slot=2)
    with pytest.raises(OutOfPages):
        kv.alloc(0, 1, 100)  # beyond max_pages_per_slot
    assert not kv.can_admit(100)
    kv.alloc(0, 1, 4)
    with pytest.raises(ValueError):
        kv.alloc(0, 1, 4)  # already active
    with pytest.raises(ValueError):
        kv.append(1)  # not active


# -- shared-prefix COW --------------------------------------------------------


def test_kvcache_prefix_sharing_cow_invariants():
    """Refcounted page sharing: aliased tables on a prefix hit, refcounts
    never negative, shared pages survive one slot's release, divergence
    mid-block allocates a private page (COW without the copy)."""
    kv = PagedKVCache(num_pages=17, page_size=4, num_slots=4,
                      max_pages_per_slot=4)
    sys9 = [7, 7, 7, 7, 1, 2, 3, 4, 9]
    # cold prompt: nothing indexed yet, everything allocated privately
    assert kv.alloc(0, sys9, 12) == (0, 0)
    assert kv.commit_prefix(0, sys9) == 2  # two full blocks published
    # second slot with the same two leading blocks shares both pages
    shared, saved = kv.alloc(1, [7, 7, 7, 7, 1, 2, 3, 4, 5], 12)
    assert (shared, saved) == (2, 8)
    t = kv.page_tables()
    assert (t[0, :2] == t[1, :2]).all()   # aliased prefix pages
    assert t[0, 2] != t[1, 2]             # divergent tail page is private
    rc = kv.refcounts()
    assert rc[t[0, 0]] == 2 and rc[t[0, 1]] == 2
    assert rc[t[0, 2]] == 1 and rc[t[1, 2]] == 1
    # releasing one owner decrements, never frees a still-shared page
    kv.free(0)
    rc = kv.refcounts()
    assert (rc >= 0).all()
    assert rc[t[1, 0]] == 1 and rc[t[1, 1]] == 1
    assert kv.stats()["pages_used"] == 3
    # releasing the last owner retires everything; indexed pages park in the
    # cached tier but stay reclaimable, so pages_free sees the whole pool
    kv.free(1)
    st = kv.stats()
    assert st["pages_used"] == 0 and st["pages_free"] == 16
    assert st["pages_cached"] == 2
    assert (kv.refcounts() == 0).all()
    # revival + mid-block divergence: first block hits (revived from the
    # cached tier), second block differs inside the page -> private page
    shared, saved = kv.alloc(2, [7, 7, 7, 7, 1, 2, 99, 100, 3], 12)
    assert (shared, saved) == (1, 4)
    t = kv.page_tables()
    assert kv.refcounts()[t[2, 0]] == 1
    assert kv.stats()["prefix_hits"] >= 2
    kv.free(2)
    assert kv.stats()["pages_used"] == 0


def test_kvcache_admission_exact_with_sharing():
    """can_admit/alloc account for shared pages exactly: a request that
    doesn't fit cold fits once its prefix pages are shared, and the pages it
    does NOT consume stay admittable — never double-reserved."""
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=3,
                      max_pages_per_slot=8)
    base = list(range(8))
    kv.alloc(0, base, 8)  # 2 pages, no reservation
    kv.commit_prefix(0, base)
    # 28 tokens = 7 pages > 6 free, cold -> refuse; with 2 shared -> admit
    assert not kv.can_admit(28)
    assert kv.can_admit(28, base + [1, 2])
    shared, saved = kv.alloc(1, base + [1, 2], 28)
    assert (shared, saved) == (2, 8)
    st = kv.stats()
    # slot 1 holds 3 pages (2 shared + 1 private) and reserves 4 more for
    # growth to 28 tokens; exactly one un-reserved page remains
    assert st["pages_reserved"] == 4
    assert kv.can_admit(4)
    assert not kv.can_admit(8)
    kv.free(1)
    kv.free(0)
    assert kv.stats()["pages_reserved"] == 0


def test_kvcache_no_leak_under_prefix_churn():
    """200 iterations of random alloc/commit/append/free with prefix reuse:
    refcounts never go negative and the pool drains back to empty."""
    kv = PagedKVCache(num_pages=33, page_size=4, num_slots=4,
                      max_pages_per_slot=8)
    rs = np.random.RandomState(1)
    prefixes = [list(rs.randint(1, 50, size=8)) for _ in range(3)]
    live = {}
    for _ in range(200):
        slot = kv.free_slot()
        if slot is not None and rs.rand() < 0.6:
            pref = prefixes[rs.randint(len(prefixes))]
            prompt = pref + list(rs.randint(1, 50, size=rs.randint(1, 9)))
            total = len(prompt) + int(rs.randint(1, 8))
            if kv.can_admit(total, prompt):
                kv.alloc(slot, prompt, total)
                kv.commit_prefix(slot, prompt)
                live[slot] = (len(prompt), total)
        for s in list(live):
            ln, total = live[s]
            if ln < total and rs.rand() < 0.7:
                kv.append(s)
                live[s] = (ln + 1, total)
            elif rs.rand() < 0.3:
                kv.free(s)
                del live[s]
        assert (kv.refcounts() >= 0).all()
    for s in list(live):
        kv.free(s)
    st = kv.stats()
    assert st["pages_used"] == 0 and st["pages_reserved"] == 0
    assert st["pages_free"] == 32 and st["tokens"] == 0
    assert (kv.refcounts() == 0).all()
    assert st["prefix_hits"] > 0  # the churn actually exercised sharing


# -- speculative rollback: truncate -------------------------------------------


def test_kvcache_truncate_basic_and_reservation_neutral():
    """Rollback releases whole pages past the boundary back into the
    RESERVATION (not the pool), so accept/reject churn re-draws them without
    new admission; no-op and bounds behavior pinned."""
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=2,
                      max_pages_per_slot=4)
    kv.alloc(0, prompt_tokens=6, total_tokens=16)  # holds 2, reserves 2
    kv.append(0, 5)                                # 11 tokens -> 3 pages
    assert kv.length(0) == 11 and kv.stats()["pages_used"] == 3
    assert kv.truncate(0, 7) == []                 # all-private: no copies
    assert kv.length(0) == 7 and kv.stats()["pages_used"] == 2
    # the released page is reservation again: growth to the admitted worst
    # case still never raises, and past it still does
    kv.append(0, 9)                                # 7 -> 16, the reservation
    assert kv.length(0) == 16
    with pytest.raises(OutOfPages):
        kv.append(0)
    assert kv.truncate(0, 16) == []                # n == length: no-op
    with pytest.raises(ValueError):
        kv.truncate(0, 0)
    with pytest.raises(ValueError):
        kv.truncate(0, 17)
    with pytest.raises(ValueError):
        kv.truncate(1, 1)                          # inactive slot
    kv.free(0)
    assert kv.stats()["pages_used"] == 0 and kv.stats()["pages_free"] == 8


def test_kvcache_truncate_shared_tail_cow_unalias():
    """A rollback whose new tail lands mid a SHARED page must un-alias it
    via the COW path — the truncating slot gets a private page to write,
    the other owner keeps the original, and the caller is told to copy."""
    m = Metrics()
    kv = PagedKVCache(num_pages=17, page_size=4, num_slots=3,
                      max_pages_per_slot=4, metrics=m)
    base = [7, 7, 7, 7, 1, 2, 3, 4]                # two full blocks
    kv.alloc(0, base, 12)
    kv.commit_prefix(0, base)
    shared, _ = kv.alloc(1, base + [9], 12)
    assert shared == 2
    t = kv.page_tables().copy()
    copies = kv.truncate(1, 6)                     # mid the shared 2nd page
    assert len(copies) == 1
    src, dst = copies[0]
    assert src == t[1, 1] and dst != src
    t2 = kv.page_tables()
    assert t2[1, 1] == dst and t2[0, 1] == src     # slot 0 untouched
    rc = kv.refcounts()
    assert rc[src] == 1 and rc[dst] == 1 and rc[t2[0, 0]] == 2
    assert m.summary()["counters"]["serving/kv/cow_unaliases"] == 1
    kv.free(0)
    kv.free(1)
    assert (kv.refcounts() == 0).all()
    assert kv.stats()["pages_used"] == 0


def test_kvcache_truncate_deregisters_indexed_exclusive_tail():
    """Rolling back mid an indexed-but-exclusive page deregisters it from
    the prefix index: the slot is about to overwrite contents the index
    still advertises."""
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=2,
                      max_pages_per_slot=2)
    base = [5, 6, 7, 8, 9, 10, 11, 12]
    kv.alloc(0, base, 8)
    kv.commit_prefix(0, base)                      # both blocks indexed
    assert kv.truncate(0, 6) == []                 # exclusive: no copy
    kv.free(0)
    shared, _ = kv.alloc(1, base, 8)               # replay the same prompt
    assert shared == 1                             # only block 0 survives
    kv.free(1)


def test_kvcache_truncate_no_leak_under_spec_churn():
    """200 iterations of speculative append-k / accept-a / truncate churn
    with prefix sharing in the mix: refcount conservation holds every
    iteration (sum of refcounts == live table entries) and the pool drains
    clean."""
    kv = PagedKVCache(num_pages=33, page_size=4, num_slots=4,
                      max_pages_per_slot=8)
    rs = np.random.RandomState(2)
    prefixes = [list(rs.randint(1, 50, size=8)) for _ in range(2)]
    live = {}
    for _ in range(200):
        slot = kv.free_slot()
        if slot is not None and rs.rand() < 0.5:
            pref = prefixes[rs.randint(len(prefixes))]
            prompt = pref + [int(x) for x in
                             rs.randint(1, 50, size=rs.randint(1, 5))]
            total = len(prompt) + int(rs.randint(4, 12))
            if kv.can_admit(total, prompt):
                kv.alloc(slot, prompt, total)
                kv.commit_prefix(slot, prompt)
                live[slot] = total
        for s in list(live):
            ln, total = kv.length(s), live[s]
            room = total - ln
            if room <= 0 or rs.rand() < 0.2:
                kv.free(s)
                del live[s]
                continue
            k = int(min(room, 1 + rs.randint(4)))  # speculative window
            kv.append(s, k)
            a = int(rs.randint(1, k + 1))          # accepted prefix
            kv.truncate(s, ln + a)                 # no-op when a == k
        rc = kv.refcounts()
        assert (rc >= 0).all()
        tables = kv.page_tables()
        held_entries = int(np.count_nonzero(tables[sorted(live)])) \
            if live else 0
        assert int(rc.sum()) == held_entries, "refcount conservation broken"
    for s in list(live):
        kv.free(s)
    st = kv.stats()
    assert st["pages_used"] == 0 and st["pages_reserved"] == 0
    assert st["pages_free"] == 32 and st["tokens"] == 0
    assert (kv.refcounts() == 0).all()


def test_kvcache_token_rooms():
    """token_rooms = committed-capacity headroom per slot: (held + reserved)
    pages minus the current length; zero for inactive lanes."""
    kv = PagedKVCache(num_pages=9, page_size=4, num_slots=2,
                      max_pages_per_slot=4)
    kv.alloc(0, prompt_tokens=6, total_tokens=14)  # held 2, reserved 2
    rooms = kv.token_rooms()
    assert rooms[0] == 10 and rooms[1] == 0
    kv.append(0, 2)
    assert kv.token_rooms()[0] == 8
    kv.truncate(0, 5)
    assert kv.token_rooms()[0] == 11
    kv.free(0)
    assert (kv.token_rooms() == 0).all()


# -- decode engine ------------------------------------------------------------


VOCAB = 61


@pytest.fixture(scope="module")
def lm():
    spec = build_registry_spec("transformer_lm", vocab_size=VOCAB, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def engine(lm):
    model, params = lm
    eng = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0)
    yield eng


def _dense_greedy(model, params, prompt, n):
    """Independent reference: greedy next-token via the full forward pass."""
    ids = list(prompt)
    out = []
    for _ in range(n):
        x = np.asarray(ids, np.int32)[None, :]
        logits = model.apply(params, {"input_ids": x}, ["logits"])["logits"]
        nxt = int(np.argmax(np.asarray(logits[0, -1])))
        out.append(nxt)
        ids.append(nxt)
    return out


def test_decode_step_dense_cache_parity(lm):
    """Single-token decode_step over the default dense cache reproduces the
    full causal forward, token by token."""
    model, params = lm
    prompt = [3, 9, 4, 1, 7]
    cache = model.init_decode_cache(1, max_len=16)
    logits_full = None
    for pos in range(len(prompt)):
        tok = jnp.asarray([prompt[pos]], jnp.int32)
        logits_full, cache = model.decode_step(
            params, cache, tok, jnp.asarray([pos], jnp.int32))
    x = np.asarray(prompt, np.int32)[None, :]
    ref = model.apply(params, {"input_ids": x}, ["logits"])["logits"]
    np.testing.assert_allclose(np.asarray(logits_full[0]),
                               np.asarray(ref[0, -1]), atol=1e-4, rtol=1e-4)


def test_engine_greedy_parity_and_zero_retrace(engine, lm):
    model, params = lm
    prompt = [5, 2, 8]
    info = engine.prefill(prompt, max_new_tokens=6, temperature=0.0)
    toks = [info["token"]]
    for _ in range(5):
        toks.extend(engine.step()[info["slot"]])
    engine.release(info["slot"])
    assert toks == _dense_greedy(model, params, prompt, 6)
    st = engine.stats()
    assert st["steady_traces"] == 0, (
        f"decode path retraced after warmup: {st}")


def test_engine_sampling_reproducible_and_varied(engine):
    r1 = [engine.prefill([4, 4], max_new_tokens=4, temperature=1.0,
                         top_k=8, seed=123)]
    for _ in range(3):
        r1.extend(engine.step()[r1[0]["slot"]])
    engine.release(r1[0]["slot"])
    r2 = [engine.prefill([4, 4], max_new_tokens=4, temperature=1.0,
                         top_k=8, seed=123)]
    for _ in range(3):
        r2.extend(engine.step()[r2[0]["slot"]])
    engine.release(r2[0]["slot"])
    t1 = [r1[0]["token"]] + r1[1:]
    t2 = [r2[0]["token"]] + r2[1:]
    assert t1 == t2  # same seed -> same sample path
    assert all(0 <= t < VOCAB for t in t1)
    assert engine.stats()["steady_traces"] == 0


def test_engine_admission_bounds(engine):
    assert engine.can_admit(2, 4)
    assert not engine.can_admit(engine.max_prompt_len + 1, 1)
    assert not engine.can_admit(2, engine.max_seq_len)


# -- prefix sharing + chunked prefill on the engine ---------------------------


@pytest.fixture(scope="module")
def engine_chunked(lm):
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8)


def _engine_greedy(eng, prompt, n):
    """Drive one request to n greedy tokens, riding out a chunked prefill
    (token=None) if the engine split the prompt. Returns (tokens, info)."""
    info = eng.prefill(prompt, max_new_tokens=n, temperature=0.0)
    toks = [] if info["token"] is None else [info["token"]]
    while len(toks) < n:
        out = eng.step()
        if info["slot"] in out:
            toks.extend(out[info["slot"]])
    eng.release(info["slot"])
    return toks[:n], info


def test_engine_prefix_sharing_greedy_parity(lm):
    """Greedy decode is bit-identical with sharing on vs off, across cold
    prompts, prefix hits, and mid-page divergence; the prefix-hit pass skips
    exactly the shared pages."""
    model, params = lm
    eng_on = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0)
    eng_off = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                           prefix_cache=False)
    sys_p = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13]
    prompts = [sys_p + [17, 18],                 # publishes the sys blocks
               sys_p + [17, 19],                 # prefix hit, new tail
               sys_p[:6] + [40, 41, 42, 43],     # diverges mid-block: cold
               [33, 21]]                         # unrelated short prompt
    for p in prompts:
        ref = _dense_greedy(model, params, p, 5)
        t_on, _ = _engine_greedy(eng_on, p, 5)
        t_off, _ = _engine_greedy(eng_off, p, 5)
        assert t_on == ref and t_off == ref, f"divergence on {p}"
    # replay the first prompt: its system prefix is indexed now, so the
    # prefill skips one full page and still lands on identical tokens
    t_on, info = _engine_greedy(eng_on, sys_p + [17, 18], 5)
    assert info["shared_tokens"] == 8
    assert t_on == _dense_greedy(model, params, sys_p + [17, 18], 5)
    assert eng_on.kv.stats()["prefix_hits"] >= 1
    assert eng_off.kv.stats()["prefix_hits"] == 0
    assert eng_on.stats()["steady_traces"] == 0
    assert eng_off.stats()["steady_traces"] == 0


def test_chunked_prefill_keeps_decode_cadence(engine_chunked, lm):
    """A long prompt arriving mid-stream prefills one chunk per step fused
    with the decode batch: the in-flight request produces a token on EVERY
    step, and the newcomer's first token lands after ceil(n/chunk) steps."""
    model, params = lm
    eng = engine_chunked
    a = eng.prefill([1, 2, 3], max_new_tokens=20, temperature=0.0)
    b = eng.prefill(list(range(1, 25)), max_new_tokens=4, temperature=0.0)
    assert b["token"] is None and b["chunked"]
    toks_a, toks_b, first_b = [a["token"]], [], None
    for i in range(19):
        out = eng.step()
        assert a["slot"] in out, f"decode cadence broken at step {i}"
        toks_a.extend(out[a["slot"]])
        if b["slot"] in out and len(toks_b) < 4:
            first_b = i if first_b is None else first_b
            toks_b.extend(out[b["slot"]])
            if len(toks_b) == 4:
                eng.release(b["slot"])
    eng.release(a["slot"])
    assert first_b == 2  # 24 prompt tokens / chunk 8 -> 3 fused steps
    assert toks_a == _dense_greedy(model, params, [1, 2, 3], 20)
    assert toks_b == _dense_greedy(model, params, list(range(1, 25)), 4)
    assert eng.stats()["steady_traces"] == 0
    assert eng.stats()["pending_prefills"] == 0


def test_continuous_batching_shared_prefix_parity(engine_chunked, lm):
    """Batcher over a chunked, prefix-sharing engine: chunked-cold, shared
    sync-suffix, and ladder admissions interleave and every request stays
    greedy-exact against the dense forward."""
    model, params = lm
    cb = ContinuousBatcher(engine_chunked, max_queue=32)
    try:
        sysp = [11, 3, 5, 8, 2, 9, 4, 6]
        prompts = ([sysp + [i] for i in (1, 2, 3)] + [[5, 2]]
                   + [sysp + [4, i] for i in (7, 9)])
        budgets = [4, 6, 3, 5, 4, 6]
        futs = [cb.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(prompts, budgets)]
        for p, n, f in zip(prompts, budgets, futs):
            r = f.result(timeout=120)
            assert r["tokens"] == _dense_greedy(model, params, p, n)
            assert r["num_tokens"] == n
        assert engine_chunked.stats()["steady_traces"] == 0
        assert engine_chunked.kv.stats()["prefix_hits"] >= 1
        assert engine_chunked.kv.stats()["slots_active"] == 0
    finally:
        cb.close()


# -- speculative decoding -----------------------------------------------------


@pytest.fixture(scope="module")
def engine_spec(lm):
    """One spec engine for the whole section (compiles are the cost):
    chunking only engages for prompts past the chunk threshold, so the
    short-prompt tests see plain speculative behavior on the same engine."""
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, spec_k=3)


@pytest.fixture(scope="module")
def draft_lm():
    spec = build_registry_spec("transformer_lm", vocab_size=VOCAB, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=32, dropout=0.0)
    dm = model_from_json(spec)
    return dm, dm.init(jax.random.PRNGKey(3))


def test_spec_greedy_parity_self_draft(engine_spec, lm):
    """Self-speculation must be a pure schedule change: every greedy token
    identical to the dense forward, zero steady-state retraces, and the
    stats block alive."""
    model, params = lm
    for prompt in ([5, 2, 8], [3]):
        toks, _ = _engine_greedy(engine_spec, prompt, 8)
        assert toks == _dense_greedy(model, params, prompt, 8)
    st = engine_spec.stats()
    assert st["steady_traces"] == 0
    sp = st["spec"]
    assert sp["enabled"] and sp["mode"] == "self" and sp["steps"] > 0
    assert sp["proposed"] > 0 and 0.0 <= sp["accept_rate"] <= 1.0
    assert 0.0 <= sp["mean_accepted"] <= engine_spec.spec_k


def test_spec_step_burst_contract(engine_spec):
    """step() returns 1..k+1 tokens per live slot and tokens_out accounts
    for every burst token."""
    eng = engine_spec
    before = eng.stats()["tokens_out"]
    infos = [eng.prefill([i + 1, i + 2], max_new_tokens=12, temperature=0.0)
             for i in range(2)]
    n = 0  # tokens_out counts step-produced tokens; prefill's is separate
    for _ in range(3):
        out = eng.step()
        assert set(out) == {i["slot"] for i in infos}
        for burst in out.values():
            assert 1 <= len(burst) <= eng.spec_k + 1
            n += len(burst)
    for i in infos:
        eng.release(i["slot"])
    assert eng.stats()["tokens_out"] - before == n


def test_spec_parity_with_prefix_hits_and_chunked_prefill(engine_spec, lm):
    """Speculation composed with BOTH shared-prefix caching and chunked
    prefill: replayed system prompts hit the prefix cache, a long prompt
    prefills in chunks, and every token stays greedy-exact."""
    model, params = lm
    eng = engine_spec
    sysp = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13, 12, 10]
    prompts = [sysp + [17, 18],
               list(range(1, 25))]  # 24 tokens: chunked admission
    for p in prompts:
        toks, _ = _engine_greedy(eng, p, 6)
        assert toks == _dense_greedy(model, params, p, 6)
    # replay: prefix hit and speculation in the same request
    toks, info = _engine_greedy(eng, sysp + [17, 18], 6)
    assert info["shared_tokens"] == 8
    assert toks == _dense_greedy(model, params, sysp + [17, 18], 6)
    st = eng.stats()
    assert eng.kv.stats()["prefix_hits"] >= 1
    assert st["steady_traces"] == 0 and st["pending_prefills"] == 0
    assert st["spec"]["steps"] > 0


def test_spec_greedy_parity_external_draft(lm, draft_lm):
    """A separately supplied small draft model proposes; the target's
    verify keeps the text greedy-exact even when most drafts are rejected
    (the rollback/truncate path runs constantly here)."""
    model, params = lm
    dm, dparams = draft_lm
    eng = DecodeEngine(model, params, num_slots=2, page_size=8, seed=0,
                       spec_k=2, draft_model=dm, draft_params=dparams)
    for prompt in ([5, 2, 8], [4, 4]):
        toks, _ = _engine_greedy(eng, prompt, 8)
        assert toks == _dense_greedy(model, params, prompt, 8)
    st = eng.stats()
    assert st["spec"]["mode"] == "external"
    assert st["steady_traces"] == 0


def test_spec_ctor_validation(lm, draft_lm):
    model, params = lm
    dm, dparams = draft_lm
    with pytest.raises(ValueError):  # draft knobs without spec_k
        DecodeEngine(model, params, num_slots=2, page_size=8,
                     draft_layers=1, warmup=False)
    with pytest.raises(ValueError):  # external draft without its params
        DecodeEngine(model, params, num_slots=2, page_size=8, spec_k=2,
                     draft_model=dm, warmup=False)
    with pytest.raises(ValueError):  # truncated stack deeper than the model
        DecodeEngine(model, params, num_slots=2, page_size=8, spec_k=2,
                     draft_layers=5, warmup=False)


def test_batcher_timing_decomposition_with_bursts(engine_spec, lm):
    """Per-request timing legs must sum exactly to the total with
    multi-token speculative bursts and queue waits in play — the old
    decomposition charged queue wait to prefill and assumed one token per
    step."""
    cb = ContinuousBatcher(engine_spec, max_queue=16)
    try:
        futs = [cb.submit([i + 1, i + 2, i + 3], max_new_tokens=5,
                          temperature=0.0) for i in range(6)]
        for f in futs:
            r = f.result(timeout=120)
            assert r["num_tokens"] == 5  # burst overshoot discarded
            t = f.timing
            assert t["tokens"] == 5
            assert t["queue_wait_ms"] >= 0.0 and t["prefill_ms"] > 0.0
            assert t["decode_ms"] >= 0.0
            assert (t["queue_wait_ms"] + t["prefill_ms"] + t["decode_ms"]
                    == pytest.approx(t["total_ms"], abs=1e-6))
        # 6 requests over 4 slots: somebody actually waited in the queue
        assert any(f.timing["queue_wait_ms"] > 0.0 for f in futs)
        assert engine_spec.kv.stats()["slots_active"] == 0
    finally:
        cb.close()


def test_batcher_eos_mid_burst_discards_remainder(engine_spec, lm):
    """eos landing inside a speculative burst retires the request at the
    eos token; the burst remainder is discarded, not delivered. The tiny
    model greedy-decodes to a fixed point, so the self-draft accepts in
    full: the first step burst carries spec_k + 1 tokens and eos fires on
    its first one — without mid-burst retirement the response would carry
    the whole burst."""
    model, params = lm
    ref = _dense_greedy(model, params, [5, 2, 8], 12)
    eos = ref[1]  # prefill's first token is (by design) not eos-checked
    cb = ContinuousBatcher(engine_spec, max_queue=8)
    try:
        r = cb.generate([5, 2, 8], max_new_tokens=20, eos_id=eos,
                        timeout=120)
        assert r["tokens"] == ref[:2]
        assert r["num_tokens"] == 2
        assert r["finish_reason"] == "eos"
    finally:
        cb.close()


# -- continuous batching ------------------------------------------------------


def test_continuous_batching_mixed_lengths_parity(engine, lm):
    """Mixed prompt/generation lengths join and retire mid-flight; every
    request's greedy tokens must match the dense forward exactly, and the
    fixed-shape decode step must never retrace."""
    model, params = lm
    cb = ContinuousBatcher(engine, max_queue=32)
    try:
        prompts = [[3, 1, 4], [1, 5], [9, 2, 6, 5, 3, 5], [8], [7, 9],
                   [2, 7, 1, 8]]
        budgets = [3, 7, 2, 9, 5, 4]
        futs = [cb.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(prompts, budgets)]
        for p, n, f in zip(prompts, budgets, futs):
            r = f.result(timeout=120)
            assert r["tokens"] == _dense_greedy(model, params, p, n)
            assert r["num_tokens"] == n
            assert r["finish_reason"] == "length"
            assert f.timing["tokens"] == n
        assert engine.stats()["steady_traces"] == 0
        assert engine.kv.stats()["pages_used"] == 0  # all retired
    finally:
        cb.close()


def test_continuous_batching_eos_retires_early(engine, lm):
    model, params = lm
    # find the greedy fixed point so eos actually fires mid-stream
    eos = _dense_greedy(model, params, [5, 2, 8], 6)[-1]
    cb = ContinuousBatcher(engine, max_queue=8)
    try:
        r = cb.generate([5, 2, 8], max_new_tokens=20, eos_id=eos,
                        timeout=120)
        assert r["finish_reason"] == "eos"
        assert r["tokens"][-1] == eos
        assert r["num_tokens"] < 20
    finally:
        cb.close()


def test_continuous_batching_queue_full(engine):
    cb = ContinuousBatcher(engine, max_queue=1)
    try:
        # Park an unadmittable request at the head of the queue: its page
        # reservation exceeds the whole pool, so the decode loop leaves it
        # pending forever and the queue stays full. (Can't hold cb._cond
        # around submit() instead — the condition wraps a plain Lock.)
        blocker = types.SimpleNamespace(
            prompt=[0] * engine.max_prompt_len,
            max_new_tokens=engine.max_seq_len)
        with cb._cond:
            cb._pending.append(blocker)
        assert not engine.can_admit(len(blocker.prompt),
                                    blocker.max_new_tokens)
        with pytest.raises(QueueFull):
            cb.submit([1], max_new_tokens=1)
        with cb._cond:
            cb._pending.remove(blocker)
    finally:
        cb.close()


def test_continuous_batching_drain_under_load(engine):
    """begin_drain mid-generation: queued + in-flight work completes, new
    submits are refused with Draining, wait_drained goes idle."""
    cb = ContinuousBatcher(engine, max_queue=32)
    try:
        futs = [cb.submit([i + 1, i + 2], max_new_tokens=8)
                for i in range(6)]  # 6 requests > 4 slots: some stay queued
        cb.begin_drain()
        with pytest.raises(Draining):
            cb.submit([1], max_new_tokens=1)
        assert cb.wait_drained(timeout=120)
        for f in futs:
            r = f.result(timeout=1)  # already resolved by the drain
            assert r["num_tokens"] == 8
        assert cb.depth() == 0 and cb.inflight_rows() == 0
        assert engine.kv.stats()["slots_active"] == 0
    finally:
        cb.close()


def test_continuous_batching_validates_requests(engine):
    cb = ContinuousBatcher(engine, max_queue=4)
    try:
        with pytest.raises(ValueError):
            cb.submit([], max_new_tokens=1)
        with pytest.raises(ValueError):
            cb.submit([1], max_new_tokens=0)
        with pytest.raises(ValueError):
            cb.submit([1] * (engine.max_prompt_len + 1), max_new_tokens=1)
        with pytest.raises(ValueError):
            cb.submit([1], max_new_tokens=engine.max_seq_len)
    finally:
        cb.close()


# -- HTTP front ---------------------------------------------------------------


class _EchoEngine:
    """Minimal predict engine so InferenceServer's predict side stays up."""
    max_batch = 4

    def predict(self, x):
        return np.asarray(x)


def test_generate_endpoint_end_to_end(engine, lm):
    model, params = lm
    cb = ContinuousBatcher(engine, max_queue=32)
    srv = InferenceServer(_EchoEngine(), generate_batcher=cb, port=0).start()
    try:
        cli = ServingClient(srv.url, timeout=60)
        r = cli.generate([3, 1, 4], max_new_tokens=5, request_id="req-42")
        assert r["tokens"] == _dense_greedy(model, params, [3, 1, 4], 5)
        assert r["finish_reason"] == "length"
        assert r["request_id"] == "req-42"
        assert r["x_request_id_header"] == "req-42"
        assert set(r["timing_ms"]) >= {"prefill_ms", "decode_ms", "total_ms"}
        # healthz reports the decode plane
        h = cli.healthz()
        assert h["decode"]["engine"]["steady_traces"] == 0
        assert h["decode"]["queue_depth"] == 0
        # malformed bodies are structured 400s, id still echoed
        with pytest.raises(ServingError) as ei:
            cli.generate([], max_new_tokens=1)
        assert ei.value.status == 400
        with pytest.raises(ServingError) as ei:
            cli.generate([1], max_new_tokens=10_000)  # beyond max_seq_len
        assert ei.value.status == 400
    finally:
        srv.stop()


def test_generate_404_without_batcher():
    srv = InferenceServer(_EchoEngine(), port=0).start()
    try:
        cli = ServingClient(srv.url, timeout=10)
        with pytest.raises(ServingError) as ei:
            cli.generate([1, 2], retries=0)
        assert ei.value.status == 404
    finally:
        srv.stop()


def test_server_drain_rejects_generate(engine):
    cb = ContinuousBatcher(engine, max_queue=8)
    srv = InferenceServer(_EchoEngine(), generate_batcher=cb, port=0).start()
    try:
        cli = ServingClient(srv.url, timeout=30)
        srv.drain(timeout=30)
        with pytest.raises(ServingError) as ei:
            cli.generate([1, 2], retries=0)
        assert ei.value.status == 503
    finally:
        srv.stop()


# -- model-parallel decode: tp/ep over the sharded pool -----------------------


@pytest.fixture(scope="module")
def tp_mesh():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    return make_mesh({"tp": 2}, devices=jax.devices()[:2])


@pytest.fixture(scope="module")
def engine_tp(lm, tp_mesh):
    """One tensor-parallel engine for the section, with speculation AND
    chunked prefill on — every decode feature rides the sharded pool."""
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, spec_k=3, mesh=tp_mesh,
                       sharding=ShardingConfig(tp_axis="tp"))


def test_tp_kernel_heads_sharded_parity(tp_mesh):
    """The pallas kernels under a heads-axis shard_map — each shard sees its
    own head slice, identical slot/page grid — match the unsharded kernel.
    Attention is per-head independent, so the split must be exact."""
    rs = np.random.RandomState(0)
    b, h, d, page_size, max_pages = 2, 4, 8, 8, 2
    q, k, v, table, lens = _rand_paged(rs, b, h, d, page_size, max_pages,
                                       [5, 11])
    full = np.asarray(paged_attention(q, k, v, table, lens, interpret=True))
    fn = shard_map(
        lambda q, k, v, t, l: paged_attention(q, k, v, t, l, interpret=True),
        mesh=tp_mesh,
        in_specs=(P(None, "tp", None), P(None, None, "tp", None),
                  P(None, None, "tp", None), P(), P()),
        out_specs=P(None, "tp", None), check_vma=False)
    out = np.asarray(fn(q, k, v, table, lens))
    np.testing.assert_allclose(out, full, atol=1e-6, rtol=1e-6)

    # the multi-query verify kernel shards on the same heads axis
    s = 3
    qv, kv_, vv, tablev, starts = _rand_paged_verify(
        rs, b, h, s, d, page_size, 4, [0, 5])
    fullv = np.asarray(paged_attention_verify(qv, kv_, vv, tablev, starts,
                                              interpret=True))
    fnv = shard_map(
        lambda q, k, v, t, st: paged_attention_verify(q, k, v, t, st,
                                                      interpret=True),
        mesh=tp_mesh,
        in_specs=(P(None, "tp", None, None), P(None, None, "tp", None),
                  P(None, None, "tp", None), P(), P()),
        out_specs=P(None, "tp", None, None), check_vma=False)
    outv = np.asarray(fnv(qv, kv_, vv, tablev, starts))
    np.testing.assert_allclose(outv, fullv, atol=1e-6, rtol=1e-6)


def test_tp_greedy_parity_battery(engine_tp, lm):
    """tp=2 greedy decode is token-identical to the dense forward across a
    plain prompt, a prefix-publishing prompt, a chunked-admission prompt,
    and a prefix-COW replay — speculation on throughout, zero steady-state
    retraces."""
    model, params = lm
    sysp = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13, 12, 10]
    prompts = [[5, 2, 8],            # plain short
               sysp + [17, 18],      # publishes the shared prefix blocks
               list(range(1, 25))]   # 24 tokens: chunked admission
    for p in prompts:
        toks, _ = _engine_greedy(engine_tp, p, 6)
        assert toks == _dense_greedy(model, params, p, 6)
    # replay: COW prefix hit on the *sharded* pool + speculation together
    toks, info = _engine_greedy(engine_tp, sysp + [17, 18], 6)
    assert info["shared_tokens"] == 8
    assert toks == _dense_greedy(model, params, sysp + [17, 18], 6)
    st = engine_tp.stats()
    assert st["steady_traces"] == 0, (
        f"tensor-parallel decode retraced after warmup: {st}")
    assert st["spec"]["steps"] > 0
    assert engine_tp.kv.stats()["prefix_hits"] >= 1
    par = st["parallel"]
    assert par["tp"] == 2 and par["ep"] == 1
    assert par["mesh"] == {"tp": 2}


def test_tp_sampling_reproducible(engine_tp):
    """Same seed -> same sampled path on the sharded engine (the sampler
    consumes mesh-sharded logits through the same AOT plane)."""

    def run():
        info = engine_tp.prefill([4, 4], max_new_tokens=4, temperature=1.0,
                                 top_k=8, seed=123)
        toks = [] if info["token"] is None else [info["token"]]
        while len(toks) < 4:
            out = engine_tp.step()
            if info["slot"] in out:
                toks.extend(out[info["slot"]])
        engine_tp.release(info["slot"])
        return toks[:4]

    t1, t2 = run(), run()
    assert t1 == t2
    assert all(0 <= t < VOCAB for t in t1)
    assert engine_tp.stats()["steady_traces"] == 0


def test_tp_at_rest_bytes_halved(engine_tp, engine_spec):
    """Sharding the pool on heads halves the at-rest KV bytes per device
    exactly (same global shape, tp-way split); params shrink too. The
    baseline engine_spec is constructed identically minus the mesh."""
    sh = engine_tp.stats()["parallel"]
    ref = engine_spec.stats()["parallel"]
    assert ref["tp"] == 1 and sh["tp"] == 2
    assert sh["kv_bytes_per_device"] * 2 == ref["kv_bytes_per_device"], (
        sh, ref)
    assert sh["param_bytes_per_device"] < ref["param_bytes_per_device"]


def test_tp_ep_ctor_validation(lm, tp_mesh):
    """Indivisible heads/experts and missing pspecs surface at construction,
    before any compile."""
    model, params = lm
    if len(jax.devices()) >= 3:
        mesh3 = make_mesh({"tp": 3}, devices=jax.devices()[:3])
        with pytest.raises(ValueError):  # num_heads=4 % tp=3
            DecodeEngine(model, params, num_slots=2, page_size=8,
                         mesh=mesh3, sharding=ShardingConfig(tp_axis="tp"),
                         warmup=False)
        spec = presets.moe_lm(VOCAB, hidden=32, num_layers=2, num_heads=4,
                              mlp_dim=64, max_len=32, num_experts=4,
                              moe_every=1)
        moe = model_from_json(spec)
        mparams = moe.init(jax.random.PRNGKey(1))
        mesh_ep3 = make_mesh({"ep": 3}, devices=jax.devices()[:3])
        with pytest.raises(ValueError):  # num_experts=4 % ep=3
            DecodeEngine(moe, mparams, num_slots=2, page_size=8,
                         mesh=mesh_ep3,
                         sharding=ShardingConfig(ep_axis="ep"), warmup=False)


def test_tp_pack_params_column_perm_and_row_bias(lm):
    """The host-side relayout behind shard_map TP: rank r's contiguous
    qkv block is exactly [q_r | k_r | v_r] for ITS heads, row-parallel
    biases pre-divide by tp so the rejoin psum restores them once, and
    everything else passes through untouched."""
    from sparkflow_tpu.parallel.tp import tp_pack_params
    model, params = lm
    tp = 2
    H, d = model.num_heads, model.head_dim
    packed = tp_pack_params(model, params, tp)
    # tp=1 is the identity (same object, no copies)
    assert tp_pack_params(model, params, 1) is params
    blocks = [n for n, sub in params.items()
              if isinstance(sub, dict) and "qkv_kernel" in sub]
    assert blocks, "fixture model has no attention blocks?"
    for name in blocks:
        orig, new = params[name], packed[name]
        w = np.asarray(orig["qkv_kernel"])      # [in, 3*H*d], (3, H, d) cols
        pw = np.asarray(new["qkv_kernel"])
        cols = w.reshape(w.shape[0], 3, H, d)
        width = 3 * (H // tp) * d
        for r in range(tp):
            # the block-local reshape each rank performs inside shard_map
            block = pw[:, r * width:(r + 1) * width]
            block = block.reshape(w.shape[0], 3, H // tp, d)
            lo, hi = r * (H // tp), (r + 1) * (H // tp)
            np.testing.assert_array_equal(block, cols[:, :, lo:hi, :])
        if "qkv_bias" in orig:
            b = np.asarray(orig["qkv_bias"]).reshape(3, H, d)
            pb = np.asarray(new["qkv_bias"])
            for r in range(tp):
                lo, hi = r * (H // tp), (r + 1) * (H // tp)
                np.testing.assert_array_equal(
                    pb[r * width:(r + 1) * width].reshape(3, H // tp, d),
                    b[:, lo:hi, :])
        # row-parallel biases: psum over tp ranks must restore them once
        for bias in ("o_bias", "fc2_bias"):
            if bias in orig:
                np.testing.assert_array_equal(
                    np.asarray(new[bias]) * tp, np.asarray(orig[bias]))
        # column-natural/replicated leaves pass through untouched
        for k in orig:
            if k not in ("qkv_kernel", "qkv_bias", "o_bias", "fc2_bias"):
                np.testing.assert_array_equal(np.asarray(new[k]),
                                              np.asarray(orig[k]))
    with pytest.raises(ValueError, match="num_heads"):
        tp_pack_params(model, params, 3)  # 4 heads % 3
    q8 = {n: (dict(sub, qkv_kernel_q8=1) if isinstance(sub, dict)
              and "qkv_kernel" in sub else sub)
          for n, sub in params.items()}
    with pytest.raises(ValueError, match="quantize"):
        tp_pack_params(model, q8, tp)


def test_moe_ep_generate_endpoint_end_to_end(tp_mesh):
    """MoE decode serves end-to-end through POST /v1/generate with
    expert-parallel dispatch: the registry preset builds the model, the
    engine shards the expert banks over ('ep',), /healthz reports the mesh,
    and the text matches an unsharded engine on the same weights."""
    mesh = make_mesh({"ep": 2}, devices=jax.devices()[:2])
    spec = presets.moe_lm(VOCAB, hidden=32, num_layers=2, num_heads=4,
                          mlp_dim=64, max_len=32, num_experts=4,
                          router_top_k=2, moe_every=1)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(1))
    prompt = [3, 1, 4, 1, 5]
    ref_eng = DecodeEngine(model, params, num_slots=2, page_size=8, seed=0)
    want, _ = _engine_greedy(ref_eng, prompt, 5)
    eng = DecodeEngine(model, params, num_slots=2, page_size=8, seed=0,
                       mesh=mesh, sharding=ShardingConfig(ep_axis="ep"))
    cb = ContinuousBatcher(eng, max_queue=8)
    srv = InferenceServer(_EchoEngine(), generate_batcher=cb, port=0).start()
    try:
        cli = ServingClient(srv.url, timeout=120)
        r = cli.generate(prompt, max_new_tokens=5, request_id="moe-ep")
        assert r["tokens"] == want
        assert r["finish_reason"] == "length"
        h = cli.healthz()
        assert h["decode"]["ep"] == 2
        assert h["decode"]["mesh_shape"] == {"ep": 2}
        assert h["decode"]["engine"]["steady_traces"] == 0
    finally:
        srv.stop()


def test_inference_engine_tp_predict_parity(lm, tp_mesh):
    """The predict plane under GSPMD tensor parallelism: logits match the
    replicated engine to float tolerance, params are sharded at rest, and
    quantize + model-parallel is refused up front."""
    model, params = lm
    e1 = InferenceEngine(model, params, input_name="input_ids:0",
                         output_name="logits:0", max_batch=4)
    e2 = InferenceEngine(model, params, input_name="input_ids:0",
                         output_name="logits:0", max_batch=4, mesh=tp_mesh,
                         sharding=ShardingConfig(tp_axis="tp"))
    x = np.array([[(i * 7 + k + 1) % VOCAB for k in range(32)]
                  for i in range(3)], np.int32)
    o1, o2 = e1.predict(x), e2.predict(x)
    np.testing.assert_allclose(o1, o2, atol=1e-4, rtol=1e-4)
    s = e2.stats()
    assert s["tp"] == 2 and s["ep"] == 1
    assert s["param_bytes_per_device"] < e1.stats()["param_bytes_per_device"]
    assert s["steady_traces"] == 0
    with pytest.raises(ValueError, match="quantize"):
        InferenceEngine(model, params, input_name="input_ids:0",
                        output_name="logits:0", max_batch=4, mesh=tp_mesh,
                        sharding=ShardingConfig(tp_axis="tp"),
                        quantize="weight_only")


def test_decode_lint_planted_defects_both_directions(tp_mesh):
    """GC-J106 on the decode plane fires both ways: a declared tp axis with
    no rejoin psum, and a rogue psum over an undeclared axis."""
    x = jnp.ones((4,), jnp.float32)

    def no_rejoin(v):
        return v * 2.0

    found = jaxpr_lint.lint_decode_collectives(
        no_rejoin, (x,), mesh=tp_mesh, in_specs=(P(),), out_specs=P(),
        tp_axis="tp")
    assert any(f.rule == "GC-J106" for f in found), found

    def rogue(v):
        return jax.lax.psum(v, "tp")

    found = jaxpr_lint.lint_decode_collectives(
        rogue, (x,), mesh=tp_mesh, in_specs=(P(),), out_specs=P())
    assert any(f.rule == "GC-J106" for f in found), found
    # and the ignore escape hatch silences it
    assert jaxpr_lint.lint_decode_collectives(
        rogue, (x,), mesh=tp_mesh, in_specs=(P(),), out_specs=P(),
        ignore=("GC-J106",)) == []


def test_decode_lint_repo_clean(engine, engine_tp):
    """The repo's own decode step passes the lint sharded and unsharded:
    the sharded engine shows the psum rejoin on its declared axis, the
    TP-less engine shows no collectives at all."""
    assert jaxpr_lint.lint_decode_step(engine) == []
    assert jaxpr_lint.lint_decode_step(engine_tp) == []


# -- pipeline-parallel decode: stage-sharded pool + wave scheduling -----------


@pytest.fixture(scope="module")
def pp_mesh():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    return make_mesh({"pp": 2}, devices=jax.devices()[:2])


@pytest.fixture(scope="module")
def engine_pp(lm, pp_mesh):
    """Stage-sharded engine with speculation AND chunked prefill on. spec_k
    forces the single-wave schedule (the verify chunk already amortizes
    depth), so this fixture exercises the staged ladder/suffix/draft/verify
    programs."""
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, spec_k=3, mesh=pp_mesh,
                       sharding=ShardingConfig(pp_axis="pp"))


@pytest.fixture(scope="module")
def engine_pp_wave(lm, pp_mesh):
    """Wave-scheduled pp engine: no speculation, so the micro-token wave
    tick carries steady-state decode (chunked prefill still on — admission
    drains the waves around each fused chunk step)."""
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, mesh=pp_mesh,
                       sharding=ShardingConfig(pp_axis="pp"))


def test_pp_greedy_parity_battery(engine_pp, lm):
    """pp=2 greedy decode is token-identical to the dense forward across a
    plain prompt, a prefix-publishing prompt, a chunked-admission prompt,
    and a prefix-COW replay — speculation on throughout (single-wave
    schedule), zero steady-state retraces."""
    model, params = lm
    sysp = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13, 12, 10]
    prompts = [[5, 2, 8],            # plain short
               sysp + [17, 18],      # publishes the shared prefix blocks
               list(range(1, 25))]   # 24 tokens: chunked admission
    for p in prompts:
        toks, _ = _engine_greedy(engine_pp, p, 6)
        assert toks == _dense_greedy(model, params, p, 6)
    # replay: COW prefix hit on the *layers-sharded* pool + speculation
    toks, info = _engine_greedy(engine_pp, sysp + [17, 18], 6)
    assert info["shared_tokens"] == 8
    assert toks == _dense_greedy(model, params, sysp + [17, 18], 6)
    st = engine_pp.stats()
    assert st["steady_traces"] == 0, (
        f"pipeline-parallel decode retraced after warmup: {st}")
    assert st["spec"]["steps"] > 0
    assert engine_pp.kv.stats()["prefix_hits"] >= 1
    par = st["parallel"]
    assert par["pp"] == 2 and par["stages"] == 2 and par["tp"] == 1
    assert par["mesh"] == {"pp": 2}
    assert par["pp_wave"] is False  # spec_k stands the waves down


def test_pp_wave_concurrent_parity(engine_pp_wave, lm):
    """Micro-token wave scheduling: four mixed-length slots fill both
    waves of the pipeline, every stream stays token-identical to the dense
    forward, and a chunked admission mid-decode drains/refills the waves
    without disturbing in-flight streams. One tick executable, zero
    steady-state retraces."""
    model, params = lm
    eng = engine_pp_wave
    prompts = [[5, 2, 8], [1, 2, 3, 4, 5, 6, 7], [9], [4, 4]]
    refs = [_dense_greedy(model, params, p, 5) for p in prompts]
    infos = [eng.prefill(p, max_new_tokens=5, temperature=0.0)
             for p in prompts]
    got = {i["slot"]: [i["token"]] for i in infos}
    guard = 0
    while any(len(v) < 5 for v in got.values()):
        for s, ts in eng.step().items():
            got[s].extend(ts)
        guard += 1
        assert guard < 300, "wave decode made no progress"
    for info, p, ref in zip(infos, prompts, refs):
        assert got[info["slot"]][:5] == ref, p
        eng.release(info["slot"])
    # chunked admission while a wave stream decodes: the fused chunk step
    # drains the in-flight waves, runs flat, and the waves refill after
    long_p = list(range(2, 27))
    info_a = eng.prefill([5, 2, 8], max_new_tokens=8, temperature=0.0)
    info_b = eng.prefill(long_p, max_new_tokens=4, temperature=0.0)
    assert info_b["chunked"] and info_b["token"] is None
    got_a, got_b = [info_a["token"]], []
    guard = 0
    while len(got_a) < 8 or len(got_b) < 4:
        r = eng.step()
        got_a.extend(r.get(info_a["slot"], []))
        got_b.extend(r.get(info_b["slot"], []))
        guard += 1
        assert guard < 500
    eng.release(info_a["slot"])
    eng.release(info_b["slot"])
    assert got_a[:8] == _dense_greedy(model, params, [5, 2, 8], 8)
    assert got_b[:4] == _dense_greedy(model, params, long_p, 4)
    st = eng.stats()
    assert st["steady_traces"] == 0, st
    par = st["parallel"]
    assert par["pp_wave"] is True and par["wave_ticks"] > 0


def test_pp_wave_sampling_reproducible(engine_pp_wave):
    """Same seed -> same sampled path through the wave tick plane (the
    exit-wave logits ride the same select-psum as greedy)."""

    def run():
        info = engine_pp_wave.prefill([4, 4], max_new_tokens=4,
                                      temperature=1.0, top_k=8, seed=123)
        toks = [] if info["token"] is None else [info["token"]]
        while len(toks) < 4:
            out = engine_pp_wave.step()
            if info["slot"] in out:
                toks.extend(out[info["slot"]])
        engine_pp_wave.release(info["slot"])
        return toks[:4]

    t1, t2 = run(), run()
    assert t1 == t2
    assert all(0 <= t < VOCAB for t in t1)
    assert engine_pp_wave.stats()["steady_traces"] == 0


def test_pp_at_rest_bytes_halved(engine_pp, engine_spec):
    """Sharding the pool on its layers axis halves the at-rest KV bytes
    per device exactly (same global shape, pp-way split on layers); the
    stage-stacked params shrink too. engine_spec is the identical
    construction minus the mesh."""
    sh = engine_pp.stats()["parallel"]
    ref = engine_spec.stats()["parallel"]
    assert ref["pp"] == 1 and sh["pp"] == 2
    assert sh["kv_bytes_per_device"] * 2 == ref["kv_bytes_per_device"], (
        sh, ref)
    assert sh["param_bytes_per_device"] < ref["param_bytes_per_device"]


def test_pp_tp_mesh_composition_parity(lm):
    """A 2D pp x tp mesh composes: depth-sharded stages whose blocks are
    also width-sharded serve token-identical greedy output, and per-device
    KV bytes drop by the full pp*tp factor."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    model, params = lm
    mesh2d = make_mesh({"pp": 2, "tp": 2}, devices=jax.devices()[:4])
    eng = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       mesh=mesh2d,
                       sharding=ShardingConfig(pp_axis="pp", tp_axis="tp"))
    ref_eng = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0)
    for p in ([5, 2, 8], [1, 2, 3, 4, 5, 6, 7]):
        toks, _ = _engine_greedy(eng, p, 6)
        assert toks == _dense_greedy(model, params, p, 6)
    st = eng.stats()
    assert st["steady_traces"] == 0
    par, ref = st["parallel"], ref_eng.stats()["parallel"]
    assert par["pp"] == 2 and par["tp"] == 2
    assert par["mesh"] == {"pp": 2, "tp": 2}
    assert par["kv_bytes_per_device"] * 4 == ref["kv_bytes_per_device"], (
        par, ref)


def test_pp_ctor_validation(lm, pp_mesh):
    """pp misconfigurations surface at construction, before any compile:
    ragged stage depth, indivisible wave lanes, pp+ep composition, a
    draft chain that exits mid-stage, and the predict plane's refusal."""
    model, params = lm
    spec3 = build_registry_spec("transformer_lm", vocab_size=VOCAB,
                                hidden=32, num_layers=3, num_heads=4,
                                mlp_dim=64, max_len=32, dropout=0.0)
    m3 = model_from_json(spec3)
    with pytest.raises(ValueError, match="num_layers"):
        DecodeEngine(m3, m3.init(jax.random.PRNGKey(0)), num_slots=2,
                     page_size=8, mesh=pp_mesh,
                     sharding=ShardingConfig(pp_axis="pp"), warmup=False)
    with pytest.raises(ValueError, match="num_slots"):
        DecodeEngine(model, params, num_slots=3, page_size=8, mesh=pp_mesh,
                     sharding=ShardingConfig(pp_axis="pp"), warmup=False)
    # draft_layers=1 is a whole stage here (stage depth 1): must pass the
    # gate; an over-deep model with stage depth 2 and draft_layers=1 is the
    # planted failure
    spec4 = build_registry_spec("transformer_lm", vocab_size=VOCAB,
                                hidden=32, num_layers=4, num_heads=4,
                                mlp_dim=64, max_len=32, dropout=0.0)
    m4 = model_from_json(spec4)
    with pytest.raises(ValueError, match="stage boundary"):
        DecodeEngine(m4, m4.init(jax.random.PRNGKey(0)), num_slots=4,
                     page_size=8, mesh=pp_mesh,
                     sharding=ShardingConfig(pp_axis="pp"),
                     spec_k=2, draft_layers=1, warmup=False)
    if len(jax.devices()) >= 4:
        mspec = presets.moe_lm(VOCAB, hidden=32, num_layers=2, num_heads=4,
                               mlp_dim=64, max_len=32, num_experts=4,
                               moe_every=1)
        moe = model_from_json(mspec)
        mesh_ppep = make_mesh({"pp": 2, "ep": 2}, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="does not compose"):
            DecodeEngine(moe, moe.init(jax.random.PRNGKey(1)), num_slots=2,
                         page_size=8, mesh=mesh_ppep,
                         sharding=ShardingConfig(pp_axis="pp", ep_axis="ep"),
                         warmup=False)
    with pytest.raises(ValueError, match="pp_axis"):
        InferenceEngine(model, params, input_name="input_ids:0",
                        output_name="logits:0", max_batch=4, mesh=pp_mesh,
                        sharding=ShardingConfig(pp_axis="pp"))


def test_decode_lint_pp_planted_defects_both_directions(pp_mesh):
    """The pp direction of GC-J106: a declared pp axis whose step has no
    ppermute handoff (an exit psum alone is not a pipeline), and a rogue
    ppermute on an engine that declares no pp_axis."""
    x = jnp.ones((4,), jnp.float32)

    def no_handoff(v):
        # the exit broadcast without the stage handoff: pp joins the
        # declared reduce axes, so ONLY the missing-ppermute finding fires
        return jax.lax.psum(v, "pp")

    found = jaxpr_lint.lint_decode_collectives(
        no_handoff, (x,), mesh=pp_mesh, in_specs=(P(),), out_specs=P(),
        pp_axis="pp")
    assert len(found) == 1 and found[0].rule == "GC-J106", found
    assert "ppermute" in found[0].message

    def rogue(v):
        return jax.lax.ppermute(v, "pp", [(0, 1), (1, 0)])

    found = jaxpr_lint.lint_decode_collectives(
        rogue, (x,), mesh=pp_mesh, in_specs=(P(),), out_specs=P())
    assert any(f.rule == "GC-J106" and "depth-sharded" in f.message
               for f in found), found


def test_decode_lint_pp_repo_clean(engine_pp, engine_pp_wave):
    """The repo's own staged decode step passes the pp lint: the declared
    pp axis shows its ppermute handoff, and the exit psums over pp are
    recognized as declared rather than rogue."""
    assert jaxpr_lint.lint_decode_step(engine_pp) == []
    assert jaxpr_lint.lint_decode_step(engine_pp_wave) == []


# -- static gates -------------------------------------------------------------


SERVING_DIR = os.path.join(os.path.dirname(__file__), os.pardir,
                           "sparkflow_tpu", "serving")


@pytest.mark.parametrize("fname", ["kvcache.py", "decode.py", "batcher.py",
                                   "server.py", "membership.py"])
def test_lock_lint_clean(fname):
    """GC-L301/302/303: every shared-state write in the new serving files
    must happen under the owning lock."""
    findings = locks.lint_file(os.path.join(SERVING_DIR, fname))
    bad = [f for f in findings
           if f.rule in ("GC-L301", "GC-L302", "GC-L303")]
    assert not bad, "\n".join(f"{f.rule}: {f.message}" for f in bad)
