"""Request traces for the fleet simulator: format, I/O, and a synthetic
generator.

A trace is a time-ordered sequence of :class:`Request` records — the
*workload* half of a simulation, fully decoupled from the *fleet* half
(:mod:`sparkflow_tpu.sim.core`). Each record carries only what the router
would see at the front door: arrival time, prompt/output token counts,
tenant, and session id. Nothing about replicas or placement lives here, so
one trace replays unchanged against any what-if fleet.

The synthetic generator models the three properties of real serving
traffic that uniform Poisson misses (and that routing policies are most
sensitive to):

- **bursty arrivals** — a two-state modulated Poisson process (MMPP-2):
  the arrival rate flips between a calm base rate and ``burst_factor`` x
  that rate, with exponentially distributed dwell times. Bursts are what
  fill queues and trip breakers; a flat-rate trace never exercises either.
- **heavy-tail lengths** — prompt and output lengths draw from a bounded
  Pareto (power-law) distribution. A handful of giant requests dominate
  KV-page footprint, which is exactly the regime where byte-headroom
  routing and plain least-loaded routing diverge.
- **multi-turn sessions** — a fraction of requests continue an earlier
  session (geometric number of turns, exponential think time), carrying a
  growing prompt (the accumulated conversation). Session affinity and KV
  reuse studies need these.

Everything is driven by one ``random.Random(seed)`` — same seed, same
trace, byte for byte. Traces serialize to JSON-lines (one request per
line) via :func:`save` / :func:`load` so a trace captured from production
logs can replay through the same door.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import asdict, dataclass
from typing import Iterable, List, Optional

__all__ = ["Request", "synthetic_trace", "save", "load",
           "bounded_pareto"]


@dataclass(frozen=True)
class Request:
    """One request as the router's front door sees it.

    ``arrival_s`` is seconds from trace start (monotone non-decreasing
    across a trace). ``prompt_tokens`` / ``output_tokens`` are the true
    lengths — the simulator treats output length as an oracle (the cost
    of a request once admitted), matching how trace-driven simulators
    replay logged completions. ``turn`` counts from 0 within a session.
    """

    arrival_s: float
    prompt_tokens: int
    output_tokens: int
    tenant: str = "default"
    session: str = ""
    turn: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True,
                          separators=(",", ":"))

    @staticmethod
    def from_json(line: str) -> "Request":
        return Request(**json.loads(line))


def bounded_pareto(rng: random.Random, alpha: float, lo: int,
                   hi: int) -> int:
    """One draw from a bounded Pareto(alpha) on ``[lo, hi]`` (inverse-CDF).

    ``alpha`` near 1 is very heavy-tailed; 2-3 is moderate. Integer
    result, inclusive bounds.
    """
    if lo >= hi:
        return lo
    u = rng.random()
    la, ha = float(lo) ** alpha, float(hi) ** alpha
    # inverse CDF of the truncated Pareto
    x = (-(u * ha - u * la - ha) / (ha * la)) ** (-1.0 / alpha)
    return max(lo, min(hi, int(x)))


def synthetic_trace(num_requests: int, *, seed: int = 0,
                    rate_rps: float = 100.0,
                    burst_factor: float = 4.0,
                    burst_fraction: float = 0.1,
                    burst_dwell_s: float = 5.0,
                    prompt_alpha: float = 1.5,
                    prompt_range: (int, int) = (16, 4096),
                    output_alpha: float = 1.8,
                    output_range: (int, int) = (8, 1024),
                    session_fraction: float = 0.3,
                    mean_turns: float = 3.0,
                    think_time_s: float = 10.0,
                    tenants: int = 4) -> List[Request]:
    """Generate ``num_requests`` requests; deterministic in ``seed``.

    Arrivals follow an MMPP-2: calm rate ``rate_rps`` (scaled so the
    *time-average* rate stays ``rate_rps`` despite bursts), burst rate
    ``burst_factor`` x calm, spending ``burst_fraction`` of time bursting
    with mean dwell ``burst_dwell_s`` per visit. Lengths are bounded
    Pareto. ``session_fraction`` of non-continuation requests open a
    session whose later turns (geometric, mean ``mean_turns``) are
    injected after exponential think times with the conversation so far
    as a growing prompt. The returned list is sorted by arrival time with
    ties broken deterministically.
    """
    if num_requests <= 0:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if rate_rps <= 0.0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    rng = random.Random(seed)
    # scale the calm rate so E[rate] over both states == rate_rps
    avg_mult = (1.0 - burst_fraction) + burst_fraction * burst_factor
    calm_rate = rate_rps / avg_mult
    burst_rate = calm_rate * burst_factor
    # MMPP state machine
    bursting = False
    state_ends = rng.expovariate(
        1.0 / (burst_dwell_s * (1.0 - burst_fraction) / burst_fraction))
    now = 0.0
    out: List[Request] = []
    # session continuations scheduled for future injection:
    # (arrival_s, prompt, output, tenant, session, turn)
    pending: List[tuple] = []
    session_seq = 0
    while len(out) + len(pending) < num_requests:
        rate = burst_rate if bursting else calm_rate
        gap = rng.expovariate(rate)
        if now + gap >= state_ends:
            # flip the MMPP state at its dwell boundary, re-draw the gap
            now = state_ends
            bursting = not bursting
            dwell = (burst_dwell_s if bursting else
                     burst_dwell_s * (1.0 - burst_fraction) /
                     burst_fraction)
            state_ends = now + rng.expovariate(1.0 / dwell)
            continue
        now += gap
        prompt = bounded_pareto(rng, prompt_alpha, *prompt_range)
        output = bounded_pareto(rng, output_alpha, *output_range)
        tenant = f"tenant-{rng.randrange(tenants)}"
        if rng.random() < session_fraction:
            session_seq += 1
            sid = f"s{seed}-{session_seq}"
            out.append(Request(now, prompt, output, tenant, sid, 0))
            # geometric number of follow-up turns, mean mean_turns - 1
            turns = 0
            p_stop = 1.0 / max(1.0, mean_turns)
            t, ptoks = now, prompt
            while (rng.random() > p_stop
                   and len(out) + len(pending) < num_requests):
                turns += 1
                t += rng.expovariate(1.0 / think_time_s)
                nxt = bounded_pareto(rng, prompt_alpha, prompt_range[0],
                                     max(prompt_range[0],
                                         prompt_range[1] // 4))
                ptoks = min(prompt_range[1], ptoks + output + nxt)
                output = bounded_pareto(rng, output_alpha, *output_range)
                pending.append((t, ptoks, output, tenant, sid, turns))
        else:
            out.append(Request(now, prompt, output, tenant, "", 0))
    out.extend(Request(*p) for p in pending)
    # stable deterministic order: arrival, then the other fields
    out.sort(key=lambda r: (r.arrival_s, r.session, r.turn,
                            r.prompt_tokens, r.output_tokens))
    return out[:num_requests]


def save(path: str, trace: Iterable[Request]) -> int:
    """Write a trace as JSON-lines; returns the number of records."""
    n = 0
    with open(path, "w", encoding="utf-8") as fh:
        for req in trace:
            fh.write(req.to_json() + "\n")
            n += 1
    return n


def load(path: str, limit: Optional[int] = None) -> List[Request]:
    """Read a JSON-lines trace (optionally just the first ``limit``)."""
    out: List[Request] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            out.append(Request.from_json(line))
            if limit is not None and len(out) >= limit:
                break
    return out
