"""End-to-end estimator tests mirroring the reference suite
(``tests/dl_runner.py``): fit -> transform -> assert, pipeline save/load,
sparse inputs, direct HogwildTrainer use, optimizer configs, unsupervised mode.

Assertion style follows the reference: "learned something better than all-wrong"
(``dl_runner.py:75-88``), on the same synthetic data (overlapping Gaussians,
XOR dense + sparse)."""

import os
import random

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import (build_adam_config, build_graph,
                                       build_rmsprop_config)
from sparkflow_tpu.hogwild import HogwildSparkModel
from sparkflow_tpu.localml import (LocalSession, MulticlassClassificationEvaluator,
                                   OneHotEncoder, Pipeline, PipelineModel, Vectors)
from sparkflow_tpu.pipeline_util import PysparkPipelineWrapper
from sparkflow_tpu.tensorflow_async import SparkAsyncDL, SparkAsyncDLModel

random.seed(12345)

# Full Spark-session end-to-end fits: far too slow for the tier-1 wall-clock
# budget (each test spins a LocalSession fit/transform cycle). Run explicitly
# with `-m slow` or by file path.
pytestmark = pytest.mark.slow


# -- model builders (reference dl_runner.py:42-73) ---------------------------

def create_model():
    x = nn.placeholder([None, 2], name="x")
    y = nn.placeholder([None, 1], name="y")
    layer1 = nn.dense(x, 12, activation="relu")
    layer2 = nn.dense(layer1, 5, activation="relu")
    out = nn.dense(layer2, 1, activation="sigmoid", name="outer")
    nn.sigmoid_cross_entropy(y, out)


def create_random_model():
    x = nn.placeholder([None, 10], name="x")
    y = nn.placeholder([None, 1], name="y")
    layer1 = nn.dense(x, 12, activation="relu")
    out = nn.dense(layer1, 1, activation="sigmoid", name="outer")
    nn.log_loss(y, out)


def create_autoencoder():
    x = nn.placeholder([None, 10], name="x")
    layer1 = nn.dense(x, 5, activation="relu")
    layer2 = nn.dense(layer1, 2, activation="relu", name="out")
    layer3 = nn.dense(layer2, 5, activation="relu")
    out = nn.dense(layer3, 10, activation="sigmoid", name="outer")
    nn.mean_squared_error(x, out)


@pytest.fixture(scope="module")
def spark():
    return LocalSession.builder.appName("sparkflow-tpu-tests").master("local[2]").getOrCreate()


@pytest.fixture(scope="module")
def gaussian_df(spark):
    # two overlapping gaussians, 400 rows (reference dl_runner.py:90-95)
    rs = np.random.RandomState(12345)
    rows = []
    for _ in range(200):
        rows.append((1.0, Vectors.dense(rs.normal(2, 1, 2))))
        rows.append((0.0, Vectors.dense(rs.normal(-2, 1, 2))))
    return spark.createDataFrame(rows, ["label", "features"])


def xor_dense(spark):
    data = [(0.0, Vectors.dense(np.array([0.0, 0.0]))),
            (0.0, Vectors.dense(np.array([1.0, 1.0]))),
            (1.0, Vectors.dense(np.array([1.0, 0.0]))),
            (1.0, Vectors.dense(np.array([0.0, 1.0])))]
    return spark.createDataFrame(data, ["label", "features"])


def xor_sparse(spark):
    data = [(0.0, Vectors.sparse(2, [], [])),
            (0.0, Vectors.dense(np.array([1.0, 1.0]))),
            (1.0, Vectors.sparse(2, [0], [1.0])),
            (1.0, Vectors.sparse(2, [1], [1.0]))]
    return spark.createDataFrame(data, ["label", "features"])


def calculate_errors(df, label="label", pred="predicted"):
    return sum(1 for r in df.collect() if round(float(r[pred])) != float(r[label]))


def base_estimator(mg, **overrides):
    kw = dict(inputCol="features", tensorflowGraph=mg, tfInput="x:0",
              tfLabel="y:0", tfOutput="outer/Sigmoid:0", tfOptimizer="adam",
              tfLearningRate=.1, iters=35, partitions=2, predictionCol="predicted",
              labelCol="label", verbose=0)
    kw.update(overrides)
    return SparkAsyncDL(**kw)


def test_overlapping_gaussians(spark, gaussian_df):
    mg = build_graph(create_model)
    model = base_estimator(mg).fit(gaussian_df)
    preds = model.transform(gaussian_df)
    assert calculate_errors(preds) < 400


def test_save_model(spark, gaussian_df, tmp_path):
    mg = build_graph(create_model)
    model = base_estimator(mg).fit(gaussian_df)
    p = str(tmp_path / "model")
    model.write().overwrite().save(p)
    loaded = SparkAsyncDLModel.load(p)
    assert calculate_errors(loaded.transform(gaussian_df)) < 400


def test_save_pipeline(spark, gaussian_df, tmp_path):
    mg = build_graph(create_model)
    p = Pipeline(stages=[base_estimator(mg)]).fit(gaussian_df)
    path = str(tmp_path / "pipeline")
    p.write().overwrite().save(path)
    loaded = PysparkPipelineWrapper.unwrap(PipelineModel.load(path))
    assert calculate_errors(loaded.transform(gaussian_df)) < 400


def test_adam_optimizer_options(spark, gaussian_df):
    mg = build_graph(create_model)
    opts = build_adam_config(learning_rate=0.1, beta1=0.85, beta2=0.98, epsilon=1e-8)
    model = base_estimator(mg, optimizerOptions=opts, verbose=1).fit(gaussian_df)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_rmsprop(spark, gaussian_df):
    mg = build_graph(create_model)
    opts = build_rmsprop_config(learning_rate=0.1, decay=0.95)
    model = base_estimator(mg, tfOptimizer="rmsprop", optimizerOptions=opts).fit(gaussian_df)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_small_sparse(spark):
    mg = build_graph(create_model)
    df = xor_sparse(spark)
    model = base_estimator(mg, miniBatchSize=-1, partitions=1, iters=50).fit(df)
    assert model.transform(df).collect() is not None


def test_multi_partition_shuffle(spark, gaussian_df):
    mg = build_graph(create_model)
    model = base_estimator(mg, partitionShuffles=2, iters=15).fit(gaussian_df)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_spark_hogwild(spark):
    """Direct HogwildTrainer use, bypassing the Estimator
    (reference dl_runner.py:187-214)."""
    processed = xor_dense(spark).coalesce(1).rdd.map(
        lambda x: (np.asarray(x["features"].toArray()), x["label"]))
    mg = build_graph(create_model)
    spark_model = HogwildSparkModel(
        tensorflowGraph=mg,
        iters=10,
        tfInput="x:0",
        tfLabel="y:0",
        optimizer="adam",
        master_url="localhost:5000")
    try:
        weights = spark_model.train(processed)
        assert len(weights) > 0
    except Exception:
        spark_model.stop_server()
        raise


def test_auto_encoder(spark):
    rs = np.random.RandomState(12345)
    rows = [(Vectors.dense(rs.rand(10)),) for _ in range(100)]
    df = spark.createDataFrame(rows, ["features"])
    mg = build_graph(create_autoencoder)
    est = SparkAsyncDL(inputCol="features", tensorflowGraph=mg, tfInput="x:0",
                       tfLabel=None, tfOutput="out/Relu:0", tfOptimizer="adam",
                       tfLearningRate=.01, iters=10, predictionCol="predicted",
                       partitions=2, miniBatchSize=10, verbose=0)
    model = est.fit(df)
    encoded = model.transform(df).take(10)
    assert encoded is not None
    assert len(encoded[0]["predicted"]) == 2  # bottleneck width


def test_change_port(spark, gaussian_df, caplog):
    """port is accepted for API compatibility (no server exists to bind it);
    the documented contract is accepted-warned-ignored, so assert the
    warning, not just that fit works (the reference binds Flask to the port,
    ``HogwildSparkModel.py:244``)."""
    import logging

    mg = build_graph(create_model)
    with caplog.at_level(logging.WARNING, logger="sparkflow_tpu"):
        model = base_estimator(mg, port=3000, iters=15).fit(gaussian_df)
    assert any("port=3000 has no effect" in r.message for r in caplog.records)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_acquire_lock_warns_no_op(spark, gaussian_df, caplog):
    """acquireLock maps to the reference's RWLock-serialized PS updates
    (``tensorflow_async.py:115``); here sync all-reduce already serializes
    updates, so the Param warns that it is inert."""
    import logging

    mg = build_graph(create_model)
    with caplog.at_level(logging.WARNING, logger="sparkflow_tpu"):
        model = base_estimator(mg, acquireLock=True, iters=15).fit(gaussian_df)
    assert any("acquireLock=True has no effect" in r.message
               for r in caplog.records)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_random_model_10in(spark):
    rs = np.random.RandomState(12345)
    rows = [(float(rs.randint(0, 2)), Vectors.dense(rs.rand(10))) for _ in range(150)]
    df = spark.createDataFrame(rows, ["label", "features"])
    mg = build_graph(create_random_model)
    model = base_estimator(mg, iters=10, miniBatchSize=10,
                           miniStochasticIters=1).fit(df)
    assert calculate_errors(model.transform(df)) < 150


def test_weights_side_file_and_checkpointing(spark, gaussian_df, tmp_path):
    """Upgrade params: weightsPath (npz side-file) + checkpointDir/Every."""
    mg = build_graph(create_model)
    wp = str(tmp_path / "w")
    ck = str(tmp_path / "ck")
    est = base_estimator(mg, iters=6, weightsPath=wp, checkpointDir=ck,
                         checkpointEvery=3)
    calls = []
    est.setLossCallback(lambda loss, it, pid: calls.append((it, pid)))
    model = est.fit(gaussian_df)
    assert model.getOrDefault(model.modelWeights).startswith("npz:")
    assert calls and calls[0] == (1, 0)
    from sparkflow_tpu.checkpoint import CheckpointManager
    assert CheckpointManager(ck).all_steps()  # periodic checkpoints written
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_fit_stream_on_dp_mesh(dp_mesh):
    """Streaming ingest with the batch dimension sharded over dp."""
    import sparkflow_tpu.nn as nn2
    from sparkflow_tpu.trainer import Trainer

    def m():
        x = nn2.placeholder([None, 6], name="x")
        y = nn2.placeholder([None, 1], name="y")
        nn2.sigmoid_cross_entropy(y, nn2.dense(x, 1, name="out"))

    rs = np.random.RandomState(0)
    M = rs.randn(500, 6).astype(np.float32)
    lbl = (M @ rs.randn(6) > 0).astype(np.float32)
    tr = Trainer(build_graph(m), "x:0", "y:0", mini_batch_size=64,
                 learning_rate=0.2, mesh=dp_mesh)
    res = tr.fit_stream(zip(list(M), list(lbl)))
    assert res.losses[-1] < res.losses[0]


def test_one_hot_pipeline_accuracy(spark):
    """Full pipeline with OneHotEncoder + evaluator (examples/simple_dnn.py shape)."""
    rs = np.random.RandomState(7)
    rows = []
    for _ in range(300):
        x = rs.randn(8)
        rows.append((float(int(x[0] + 0.3 * x[1] > 0)), Vectors.dense(x)))
    df = spark.createDataFrame(rows, ["label", "features"])

    def m():
        x = nn.placeholder([None, 8], name="x")
        y = nn.placeholder([None, 2], name="y")
        h = nn.dense(x, 16, activation="relu")
        out = nn.dense(h, 2, name="out")
        nn.argmax(out, 1, name="pred")
        nn.softmax_cross_entropy(y, out)

    est = SparkAsyncDL(inputCol="features", tensorflowGraph=build_graph(m),
                       tfInput="x:0", tfLabel="y:0", tfOutput="pred:0",
                       iters=30, miniBatchSize=64, labelCol="labels",
                       predictionCol="predicted",
                       optimizerOptions=build_adam_config(learning_rate=0.01))
    pipe = Pipeline(stages=[OneHotEncoder(inputCol="label", outputCol="labels",
                                          dropLast=False), est]).fit(df)
    ev = MulticlassClassificationEvaluator(labelCol="label", predictionCol="predicted",
                                           metricName="accuracy")
    assert ev.evaluate(pipe.transform(df)) > 0.9


def test_fit_mode_stream_never_collects(spark, gaussian_df, monkeypatch):
    """fitMode='stream' must train through rdd.toLocalIterator without ever
    materializing the dataset on the driver (VERDICT r1 #2: no mandatory
    collect; reference collect site tensorflow_async.py:290-293)."""
    from sparkflow_tpu.localml.sql import RDD

    def no_collect(self):
        raise AssertionError("collect() called in stream mode")

    monkeypatch.setattr(RDD, "collect", no_collect)
    mg = build_graph(create_model)
    model = base_estimator(mg, iters=20, fitMode="stream",
                           miniBatchSize=64).fit(gaussian_df)
    monkeypatch.undo()
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_fit_mode_stream_bounded_iterator_consumption(spark):
    """The stream path pulls rows incrementally (ring-buffer granularity),
    not all upfront."""
    from sparkflow_tpu.trainer import Trainer

    pulled = []

    def rows():
        rs = np.random.RandomState(3)
        for i in range(5000):
            pulled.append(i)
            yield (rs.rand(4).astype(np.float32), float(i % 2))

    def m():
        x = nn.placeholder([None, 4], name="x")
        y = nn.placeholder([None, 1], name="y")
        nn.sigmoid_cross_entropy(y, nn.dense(x, 1, name="out"))

    pulled_at_first_step = []

    def cb(loss, it_num, pid):
        if not pulled_at_first_step:
            pulled_at_first_step.append(len(pulled))

    tr = Trainer(build_graph(m), "x:0", "y:0", mini_batch_size=32,
                 loss_callback=cb)
    res = tr.fit_stream(rows(), queue_capacity=2, chunk=64)
    assert len(pulled) == 5000 and res.losses  # every row eventually seen...
    # ...but interleaved with training: when the first step ran, the source
    # had produced at most a few chunks, not the whole dataset (a regression
    # to upfront materialization would show ~5000 here)
    assert pulled_at_first_step[0] < 1000, pulled_at_first_step


def test_param_validation_tflabel_without_labelcol(spark, gaussian_df):
    mg = build_graph(create_model)
    est = base_estimator(mg, labelCol=None)  # tfLabel still 'y:0'
    with pytest.raises(ValueError, match="labelCol is None"):
        est.fit(gaussian_df)


def test_param_validation_labelcol_without_tflabel(spark, gaussian_df):
    mg = build_graph(create_model)
    est = base_estimator(mg, tfLabel=None)  # labelCol still 'label'
    with pytest.raises(ValueError, match="tfLabel is None"):
        est.fit(gaussian_df)


def test_param_validation_bad_fit_mode(spark, gaussian_df):
    mg = build_graph(create_model)
    with pytest.raises(ValueError, match="fitMode"):
        base_estimator(mg, fitMode="warp").fit(gaussian_df)


def test_multi_input_transformer_through_estimator(spark):
    """extraInputCols/extraTfInputs: a transformer fed token ids + attention
    mask through the public estimator API (fit AND transform)."""
    from sparkflow_tpu.models import build_registry_spec

    seq, vocab = 8, 30
    spec = build_registry_spec("transformer_classifier", vocab_size=vocab,
                               num_classes=2, hidden=16, num_layers=1,
                               num_heads=2, mlp_dim=32, max_len=seq,
                               dropout=0.0)
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(60):
        label = rs.randint(0, 2)
        ids = rs.randint(3, vocab, seq)
        if label:
            ids[0] = 1  # marker token
        n_real = rs.randint(seq // 2, seq + 1)
        mask = np.zeros(seq); mask[:n_real] = 1.0
        ids[n_real:] = 0
        rows.append((float(label), Vectors.dense(ids.astype(float)),
                     Vectors.dense(mask)))
    df = spark.createDataFrame(rows, ["label", "tokens", "mask"])

    est = SparkAsyncDL(inputCol="tokens", tensorflowGraph=spec,
                       tfInput="input_ids:0", tfLabel="y:0",
                       tfOutput="pred:0", tfOptimizer="adam",
                       tfLearningRate=0.01, iters=30, partitions=2,
                       labelCol="label", predictionCol="predicted",
                       miniBatchSize=16, verbose=0,
                       extraInputCols="mask", extraTfInputs="attention_mask:0")
    model = est.fit(df)
    preds = model.transform(df)
    errs = sum(1 for r in preds.collect()
               if round(float(r["predicted"])) != float(r["label"]))
    # scalar labelCol now feeds the index path of models.base.softmax_xent
    # (a [N,1] label against [N,2] logits previously broadcast to a
    # meaningless loss, and this assertion was vacuously loose)
    assert errs < 15


def test_extra_inputs_param_validation(spark, gaussian_df):
    mg = build_graph(create_model)
    est = base_estimator(mg, extraInputCols="a,b", extraTfInputs="only_one:0")
    with pytest.raises(ValueError, match="pair up"):
        est.fit(gaussian_df)


def test_multi_input_stream_mode_through_estimator(spark):
    """fitMode='stream' + extraInputCols: multi-input rows ride the batch
    ring as concatenated tuples and split back per input before the step
    (round-2 restriction removed)."""
    from sparkflow_tpu.models import build_registry_spec

    seq, vocab = 8, 30
    spec = build_registry_spec("transformer_classifier", vocab_size=vocab,
                               num_classes=2, hidden=16, num_layers=1,
                               num_heads=2, mlp_dim=32, max_len=seq,
                               dropout=0.0)
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(60):
        label = rs.randint(0, 2)
        ids = rs.randint(3, vocab, seq)
        if label:
            ids[0] = 1  # marker token
        n_real = rs.randint(seq // 2, seq + 1)
        mask = np.zeros(seq); mask[:n_real] = 1.0
        ids[n_real:] = 0
        rows.append((float(label), Vectors.dense(ids.astype(float)),
                     Vectors.dense(mask)))
    df = spark.createDataFrame(rows, ["label", "tokens", "mask"])

    est = SparkAsyncDL(inputCol="tokens", tensorflowGraph=spec,
                       tfInput="input_ids:0", tfLabel="y:0",
                       tfOutput="pred:0", tfOptimizer="adam",
                       tfLearningRate=0.01, iters=30, partitions=2,
                       labelCol="label", predictionCol="predicted",
                       miniBatchSize=16, verbose=0, fitMode="stream",
                       extraInputCols="mask", extraTfInputs="attention_mask:0")
    model = est.fit(df)
    preds = model.transform(df)
    errs = sum(1 for r in preds.collect()
               if round(float(r["predicted"])) != float(r["label"]))
    assert errs < 15


def test_model_transform_validates_extra_pairing(spark, gaussian_df):
    mg = build_graph(create_model)
    fitted = base_estimator(mg, iters=3).fit(gaussian_df)
    fitted._set(extraInputCols="mask")  # tfInputs left unset
    with pytest.raises(ValueError, match="pair up"):
        fitted.transform(gaussian_df)


def test_old_persisted_model_without_new_params_still_transforms(spark, gaussian_df):
    """Instances dill-persisted by older versions lack newly added Params in
    their restored default map; transform/fit must treat them as defaults,
    not KeyError (forward compatibility of saved pipelines)."""
    mg = build_graph(create_model)
    model = base_estimator(mg, iters=3).fit(gaussian_df)
    # simulate a round-1 pickle: strip the round-2 Params from the maps
    for pname in ("extraInputCols", "extraTfInputs"):
        p = getattr(model, pname)
        model._defaultParamMap.pop(p, None)
        model._paramMap.pop(p, None)
    assert model.transform(gaussian_df).count() == 400

    est = base_estimator(mg, iters=2)
    for pname in ("extraInputCols", "extraTfInputs", "fitMode"):
        p = getattr(est, pname)
        est._defaultParamMap.pop(p, None)
        est._paramMap.pop(p, None)
    est.fit(gaussian_df)  # no KeyError


def test_mesh_shape_fsdp_matches_default(spark, gaussian_df):
    """meshShape opens tp/fsdp from the Param surface: a 'dp=2,fsdp=4' fit
    (ZeRO param sharding over the virtual 8-device mesh) must produce the
    SAME weights as the default dp fit — sharding is placement, not math."""
    mg = build_graph(create_model)
    m_def = base_estimator(mg, iters=12).fit(gaussian_df)
    m_fs = base_estimator(mg, iters=12, meshShape="dp=2,fsdp=4").fit(gaussian_df)
    from sparkflow_tpu.ml_util import convert_json_to_weights
    w_def = convert_json_to_weights(m_def.getOrDefault(m_def.modelWeights))
    w_fs = convert_json_to_weights(m_fs.getOrDefault(m_fs.modelWeights))
    for a, b in zip(w_def, w_fs):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_mesh_shape_dp_less_injects_dp(spark, gaussian_df):
    """A dp-less meshShape ('fsdp=8') gets a size-1 dp axis injected so the
    epoch program can shard dataset rows — the fit trains instead of dying
    in GSPMD (regression: a misindent once made the injection dead code)."""
    mg = build_graph(create_model)
    model = base_estimator(mg, iters=10, meshShape="fsdp=8").fit(gaussian_df)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_mesh_shape_validation(spark, gaussian_df):
    mg = build_graph(create_model)
    with pytest.raises(ValueError, match="unknown mesh axis"):
        base_estimator(mg, meshShape="dp=2,bogus=4").fit(gaussian_df)
    # sp/pp are estimator strategies since round 5 — but only for the model
    # families their step builders pipeline/ring over, NOT nn-DSL graphs
    with pytest.raises(ValueError, match="TransformerLM"):
        base_estimator(mg, meshShape="dp=2,sp=4").fit(gaussian_df)
    with pytest.raises(ValueError, match="block structure"):
        base_estimator(mg, meshShape="dp=2,pp=4").fit(gaussian_df)
    with pytest.raises(ValueError, match="fitMode"):
        base_estimator(mg, meshShape="dp=2,pp=4",
                       fitMode="stream").fit(gaussian_df)
    with pytest.raises(ValueError, match="param_pspecs"):
        # tp on an nn-DSL graph: no megatron rules -> must refuse, not
        # silently replicate (redundant work on every tp rank)
        base_estimator(mg, meshShape="dp=2,tp=4").fit(gaussian_df)
    with pytest.raises(ValueError, match="devices"):
        base_estimator(mg, meshShape="dp=3").fit(gaussian_df)
    with pytest.raises(ValueError, match="cannot be auto-derived"):
        base_estimator(mg, meshShape="dp=2,tp=2,fsdp=2").fit(gaussian_df)


def test_mesh_shape_tp_transformer(spark):
    """tp via meshShape on a registry transformer (has megatron rules):
    estimator-level tensor parallelism, loss-exact vs the default dp fit."""
    from sparkflow_tpu.models import build_registry_spec

    spec = build_registry_spec("transformer_classifier", vocab_size=30,
                               num_classes=2, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=8, dropout=0.0)
    rs = np.random.RandomState(7)
    rows = [(float(rs.randint(0, 2)),
             Vectors.dense(rs.randint(0, 30, 8).astype(float)))
            for _ in range(64)]
    df = spark.createDataFrame(rows, ["label", "features"])

    def est(**kw):
        return SparkAsyncDL(inputCol="features", tensorflowGraph=spec,
                            tfInput="input_ids", tfLabel="y", tfOutput="logits",
                            labelCol="label", tfOptimizer="adam",
                            tfLearningRate=.01, iters=4, miniBatchSize=16,
                            predictionCol="predicted", **kw)

    m_tp = est(meshShape="dp=2,tp=4").fit(df)
    m_dp = est().fit(df)
    from sparkflow_tpu.ml_util import convert_json_to_weights
    for a, b in zip(convert_json_to_weights(m_tp.getOrDefault(m_tp.modelWeights)),
                    convert_json_to_weights(m_dp.getOrDefault(m_dp.modelWeights))):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_fit_mode_stream_with_fsdp_mesh(spark, gaussian_df):
    """fitMode='stream' honors meshShape ZeRO sharding (stream sharding
    support landed with the meshShape Param): trains through toLocalIterator
    with params placed over fsdp, and still learns."""
    mg = build_graph(create_model)
    model = base_estimator(mg, iters=20, fitMode="stream", miniBatchSize=64,
                           meshShape="dp=2,fsdp=4").fit(gaussian_df)
    assert calculate_errors(model.transform(gaussian_df)) < 400


def test_mesh_shape_ep_moe(spark):
    """ep via meshShape on a registry MoE LM (expert banks carry P('ep',...)
    rules): estimator-level expert parallelism, weights matching the
    default replicated fit — sharding is placement, not math."""
    from sparkflow_tpu.models import build_registry_spec

    spec = build_registry_spec("transformer_moe_lm", vocab_size=30,
                               num_experts=8, moe_every=1, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=8, dropout=0.0, capacity_factor=8.0)
    rs = np.random.RandomState(9)
    rows = [(Vectors.dense(rs.randint(0, 30, 8).astype(float)),)
            for _ in range(64)]
    df = spark.createDataFrame(rows, ["features"])

    def est(**kw):
        # unsupervised: causal-LM loss over the token column itself
        return SparkAsyncDL(inputCol="features", tensorflowGraph=spec,
                            tfInput="input_ids", tfLabel=None, labelCol=None,
                            tfOutput="logits", tfOptimizer="adam",
                            tfLearningRate=.01, iters=4, miniBatchSize=16,
                            predictionCol="predicted", **kw)

    m_ep = est(meshShape="ep=8").fit(df)
    m_dp = est().fit(df)
    from sparkflow_tpu.ml_util import convert_json_to_weights
    for a, b in zip(convert_json_to_weights(m_ep.getOrDefault(m_ep.modelWeights)),
                    convert_json_to_weights(m_dp.getOrDefault(m_dp.modelWeights))):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_mesh_shape_pp_matches_default(spark):
    """pp via meshShape on a registry transformer: estimator-level pipeline
    parallelism (GPipe over the 'pp' ring composed with dp), update-exact —
    the pp fit's weights match the default dp fit because the strategy step
    slots into the SAME shuffle/batching epoch program."""
    from sparkflow_tpu.models import build_registry_spec

    spec = build_registry_spec("transformer_classifier", vocab_size=30,
                               num_classes=2, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=8, dropout=0.0)
    rs = np.random.RandomState(7)
    rows = [(float(rs.randint(0, 2)),
             Vectors.dense(rs.randint(0, 30, 8).astype(float)))
            for _ in range(64)]
    df = spark.createDataFrame(rows, ["label", "features"])

    def est(**kw):
        return SparkAsyncDL(inputCol="features", tensorflowGraph=spec,
                            tfInput="input_ids", tfLabel="y", tfOutput="logits",
                            labelCol="label", tfOptimizer="adam",
                            tfLearningRate=.01, iters=4, miniBatchSize=16,
                            predictionCol="predicted", **kw)

    m_pp = est(meshShape="dp=4,pp=2").fit(df)
    m_dp = est().fit(df)
    from sparkflow_tpu.ml_util import convert_json_to_weights
    for a, b in zip(convert_json_to_weights(m_pp.getOrDefault(m_pp.modelWeights)),
                    convert_json_to_weights(m_dp.getOrDefault(m_dp.modelWeights))):
        np.testing.assert_allclose(a, b, atol=5e-4)
    # and the fitted model serves
    assert m_pp.transform(df).count() == 64

    # the pp knobs are Params too: the 1f1b schedule with explicit
    # microbatching stays update-exact
    m_1f1b = est(meshShape="dp=4,pp=2", ppSchedule="1f1b",
                 ppMicrobatches=2).fit(df)
    for a, b in zip(
            convert_json_to_weights(m_1f1b.getOrDefault(m_1f1b.modelWeights)),
            convert_json_to_weights(m_dp.getOrDefault(m_dp.modelWeights))):
        np.testing.assert_allclose(a, b, atol=5e-4)
    with pytest.raises(ValueError, match="ppSchedule"):
        est(meshShape="dp=4,pp=2", ppSchedule="zigzag").fit(df)


def test_mesh_shape_sp_lm(spark):
    """sp via meshShape on a causal LM (ring attention over the sequence):
    estimator-level sequence parallelism. The estimator fit's weights match
    a Trainer fit on the same sp mesh/seed — the Param surface adds no
    drift — and differ from unsharded training only by the documented
    shard-boundary token exclusion (parallel/sp.py)."""
    from sparkflow_tpu.models import build_registry_spec
    from sparkflow_tpu.parallel.mesh import make_mesh
    from sparkflow_tpu.trainer import Trainer

    spec = build_registry_spec("transformer_lm", vocab_size=30, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=8, dropout=0.0)
    rs = np.random.RandomState(3)
    toks = rs.randint(0, 30, (64, 8))
    rows = [(Vectors.dense(t.astype(float)),) for t in toks]
    df = spark.createDataFrame(rows, ["features"])

    est = SparkAsyncDL(inputCol="features", tensorflowGraph=spec,
                       tfInput="input_ids", tfLabel=None, labelCol=None,
                       tfOutput="logits", tfOptimizer="adam",
                       tfLearningRate=.01, iters=4, miniBatchSize=16,
                       predictionCol="predicted", meshShape="dp=2,sp=4")
    m_sp = est.fit(df)

    mesh = make_mesh({"dp": 2, "sp": 4})
    tr = Trainer(spec, "input_ids", None, optimizer="adam",
                 learning_rate=.01, iters=4, mini_batch_size=16, mesh=mesh)
    tr.fit(toks.astype(np.float32))
    from sparkflow_tpu.graphdef import params_to_list
    from sparkflow_tpu.ml_util import convert_json_to_weights
    w_est = convert_json_to_weights(m_sp.getOrDefault(m_sp.modelWeights))
    w_tr = params_to_list(tr.model, tr.params)
    for a, b in zip(w_est, w_tr):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_use_ema_weights(spark, gaussian_df):
    """useEmaWeights: the fitted model stores the Polyak-averaged weights
    (differs from the raw-final fit, still classifies); without ema_decay
    configured it errors loudly instead of silently serving raw weights."""
    import json

    mg = build_graph(create_model)
    opts = json.dumps({"learning_rate": 0.1, "ema_decay": 0.9})
    m_ema = base_estimator(mg, iters=15, optimizerOptions=opts,
                           useEmaWeights=True).fit(gaussian_df)
    m_raw = base_estimator(mg, iters=15, optimizerOptions=opts).fit(gaussian_df)
    from sparkflow_tpu.ml_util import convert_json_to_weights
    w_ema = convert_json_to_weights(m_ema.getOrDefault(m_ema.modelWeights))
    w_raw = convert_json_to_weights(m_raw.getOrDefault(m_raw.modelWeights))
    assert any(np.abs(a - b).max() > 1e-6 for a, b in zip(w_ema, w_raw))
    assert calculate_errors(m_ema.transform(gaussian_df)) < 100

    with pytest.raises(ValueError, match="ema_decay"):
        base_estimator(mg, iters=2, useEmaWeights=True).fit(gaussian_df)


def test_model_mesh_shape_transform(spark, gaussian_df):
    """meshShape on the fitted Model: transform serves over a device mesh
    (batch over dp) with predictions identical to single-device serving,
    and composes with inferenceQuantize."""
    mg = build_graph(create_model)
    fitted = base_estimator(mg, iters=10).fit(gaussian_df)

    base = [float(r["predicted"]) for r in fitted.transform(gaussian_df).collect()]
    fitted.setParams(meshShape="dp=8")
    mesh = [float(r["predicted"]) for r in fitted.transform(gaussian_df).collect()]
    np.testing.assert_allclose(mesh, base, atol=1e-5)

    fitted.setParams(inferenceQuantize="weight_only")
    both = [float(r["predicted"]) for r in fitted.transform(gaussian_df).collect()]
    assert np.max(np.abs(np.asarray(both) - np.asarray(base))) < 0.05


def test_model_mesh_shape_validation(spark, gaussian_df):
    """Model meshShape validates on the DRIVER: non-dp axes and oversubscribed
    device counts refuse with clear messages, not executor task failures."""
    mg = build_graph(create_model)
    fitted = base_estimator(mg, iters=2).fit(gaussian_df)
    fitted.setParams(meshShape="tp=4")
    with pytest.raises(ValueError, match="data-parallel only"):
        fitted.transform(gaussian_df)
    fitted.setParams(meshShape="dp=64")
    with pytest.raises(ValueError, match="devices"):
        fitted.transform(gaussian_df)
