"""Replica cost models for the fleet simulator, fitted from measurements.

The simulator never runs a model — it *prices* each request against a
:class:`CostModel` whose coefficients come from real benchmarks
(``bench.py`` runs recorded in ``BENCH_NOTES.md``). Keeping the model
explicitly tiny (a handful of linear coefficients) is deliberate: the
point of the simulator is routing/policy dynamics at fleet scale, and for
those what matters is the *relative* cost structure (prefill scales with
prompt length, decode scales with output length and slows under
concurrency, KV pages scale with total tokens), not cycle accuracy.
:mod:`sparkflow_tpu.sim.calibrate` closes the loop by replaying the same
trace against a real fleet and pinning sim-vs-real agreement.

Default coefficients (``CostModel.from_bench_notes()``) trace to
``BENCH_NOTES.md`` entries measured on this repo's CPU rig:

- ``token_latency_p50_ms = 2.58`` (continuous-batching decode bench) —
  per-token decode step time at low concurrency.
- ``ttft_cold_ms = 10.9`` at ``prompt_len = 104`` (prefix-cache bench,
  cold path) — prefill throughput ~= 104 / (10.9 - overhead) tokens/ms.
- chunked-prefill bench: inter-token p95 rises from 2.58 p50 to
  ``p95_chunked_ms = 6.92`` when prefill and a full decode batch share
  the device — the ``decode_slowdown`` contention coefficient.
- quantized-KV bench: int8 pools hold ``3.76x`` pages per byte vs the
  float pool — why heterogeneous ``kv_bytes_per_page`` fleets exist at
  all (see the byte-headroom pick rule in ``serving/policies.py``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence

__all__ = ["CostModel"]


@dataclass(frozen=True)
class CostModel:
    """Prices one replica's work in simulated seconds.

    Parameters
    ----------
    ttft_base_ms : float
        Fixed per-request overhead before the first token (dispatch,
        dequeue, kernel launch).
    prefill_tokens_per_s : float
        Prompt tokens prefilled per second.
    decode_token_ms : float
        Per-output-token decode step time with an otherwise idle batch.
    decode_slowdown : float
        Linear contention coefficient: with ``active`` of ``slots``
        decode lanes busy, the per-token time scales by
        ``1 + decode_slowdown * active / slots``. Fitted from the
        chunked-prefill bench's p50 -> p95 spread (6.92 / 2.58 at a full
        batch => slowdown ~= 1.7).
    predict_ms : float
        Flat service time for the predict (non-autoregressive) plane;
        the same contention factor applies.
    page_size : int
        KV page granularity in tokens (matches ``PagedKVCache``).
    net_rtt_ms : float
        Router<->replica round trip added to every request's latency.
    """

    ttft_base_ms: float = 2.0
    prefill_tokens_per_s: float = 9500.0
    decode_token_ms: float = 2.58
    decode_slowdown: float = 1.7
    predict_ms: float = 12.0
    page_size: int = 16
    net_rtt_ms: float = 0.5

    @staticmethod
    def from_bench_notes() -> "CostModel":
        """The BENCH_NOTES.md-fitted defaults (see module docstring)."""
        return CostModel()

    def scaled(self, factor: float) -> "CostModel":
        """A copy with every *time* coefficient scaled by ``factor``
        (used by calibration to fit an unknown rig speed)."""
        return replace(
            self, ttft_base_ms=self.ttft_base_ms * factor,
            prefill_tokens_per_s=self.prefill_tokens_per_s / factor,
            decode_token_ms=self.decode_token_ms * factor,
            predict_ms=self.predict_ms * factor,
            net_rtt_ms=self.net_rtt_ms * factor)

    # -- pricing -----------------------------------------------------------

    def contention(self, active: int, slots: int) -> float:
        """Service-time multiplier with ``active`` of ``slots`` busy."""
        if slots <= 0:
            return 1.0
        frac = min(1.0, max(0, active) / float(slots))
        return 1.0 + self.decode_slowdown * frac

    def ttft_s(self, prompt_tokens: int, active: int, slots: int) -> float:
        """Dispatch-to-first-token time for a generate request."""
        prefill = prompt_tokens / self.prefill_tokens_per_s
        mult = self.contention(active, slots)
        return (self.ttft_base_ms + self.net_rtt_ms) / 1e3 + prefill * mult

    def decode_s(self, output_tokens: int, active: int,
                 slots: int) -> float:
        """First-token-to-done time for ``output_tokens`` tokens."""
        mult = self.contention(active, slots)
        return output_tokens * self.decode_token_ms * mult / 1e3

    def predict_s(self, active: int, slots: int) -> float:
        """Full service time for one predict request."""
        mult = self.contention(active, slots)
        return (self.predict_ms * mult + self.net_rtt_ms) / 1e3

    def pages_for(self, prompt_tokens: int, output_tokens: int) -> int:
        """KV pages a generate request pins for its lifetime."""
        total = max(1, prompt_tokens + output_tokens)
        return (total + self.page_size - 1) // self.page_size

    # -- fitting -----------------------------------------------------------

    @staticmethod
    def fit_predict(latencies_ms: Sequence[float],
                    base: Optional["CostModel"] = None) -> "CostModel":
        """Fit ``predict_ms`` from measured per-request latencies (median;
        robust to the tail the sim is supposed to *reproduce*, not
        consume as input)."""
        base = base or CostModel.from_bench_notes()
        if not latencies_ms:
            return base
        srt = sorted(float(x) for x in latencies_ms)
        med = srt[len(srt) // 2]
        # strip the modeled network RTT so it is not double counted
        return replace(base, predict_ms=max(0.1, med - base.net_rtt_ms))
