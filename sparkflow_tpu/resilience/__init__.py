"""Resilience layer: survive crashes, preemption, and corruption.

The reference's whole failure story was drop-the-update-and-print
(SURVEY.md §5); the north star — preemptible TPU pods serving production
traffic — demands the opposite. Where DeepSpark/SparkNet lean on Spark's
task-retry semantics, this repo replaced Spark executors with a JAX process
group, so the recovery machinery lives here instead:

- :mod:`~sparkflow_tpu.resilience.retry` — :class:`RetryPolicy`
  (exponential backoff + jitter + deadline) and the structured
  :class:`RetryExhausted`; reused by coordinator joins
  (``parallel.distributed.initialize``), checkpoint restore, the serving
  client, and the resilient-fit driver.
- :mod:`~sparkflow_tpu.resilience.driver` — :func:`run_resilient_fit`:
  re-invoke ``Trainer.fit`` after crashes/preemptions, resuming from the
  newest *valid* checkpoint to bit-identical final weights.
- :mod:`~sparkflow_tpu.resilience.faults` — deterministic chaos harness:
  named fault points (:func:`~faults.inject`/:func:`~faults.fire`),
  crash/SIGTERM loss_callback injectors, on-disk checkpoint corruption.
- :mod:`~sparkflow_tpu.resilience.lifecycle` — the SERVING/DRAINING state
  machine behind the HTTP front's graceful drain.

Crash-consistent checkpointing itself (tmp-dir + checksum manifest + atomic
rename, restore fallback to the newest valid step) lives in
:mod:`sparkflow_tpu.checkpoint`. See ``docs/resilience.md``.
"""

from .driver import run_resilient_fit
from .lifecycle import Lifecycle, ServerState
from .retry import RetryExhausted, RetryPolicy
from . import faults

__all__ = ["RetryPolicy", "RetryExhausted", "run_resilient_fit",
           "Lifecycle", "ServerState", "faults"]
