"""localml engine unit tests: params, vectors, dataframe, features, rwlock."""

import threading
import time

import numpy as np
import pytest

from sparkflow_tpu.localml import (DenseVector, LocalSession,
                                   MulticlassClassificationEvaluator, Normalizer,
                                   OneHotEncoder, Row, SparseVector,
                                   VectorAssembler, Vectors)
from sparkflow_tpu.localml.param import (Param, Params, TypeConverters,
                                         keyword_only)
from sparkflow_tpu.utils.rwlock import RWLock


class Thing(Params):
    alpha = Param(Params._dummy(), "alpha", "a number",
                  typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, alpha=None):
        super().__init__()
        self._setDefault(alpha=1.5)
        self._set(**self._input_kwargs)


def test_param_defaults_and_set():
    t = Thing()
    assert t.getOrDefault(t.alpha) == 1.5
    assert not t.isSet(t.alpha) and t.hasDefault(t.alpha)
    t2 = Thing(alpha="2")  # converter coerces
    assert t2.getOrDefault(t2.alpha) == 2.0
    assert t2.isSet(t2.alpha)


def test_param_copy_isolated():
    t = Thing(alpha=3.0)
    c = t.copy()
    c._set(alpha=9.0)
    assert t.getOrDefault(t.alpha) == 3.0
    assert c.getOrDefault(c.alpha) == 9.0


def test_keyword_only_rejects_positional():
    with pytest.raises(TypeError):
        Thing(2.0)


def test_vectors():
    d = Vectors.dense([1.0, 2.0, 3.0])
    s = Vectors.sparse(3, [0, 2], [1.0, 3.0])
    assert d.size == 3 and s.size == 3
    np.testing.assert_allclose(s.toArray(), [1.0, 0.0, 3.0])
    assert s[2] == 3.0 and s[1] == 0.0
    assert Vectors.sparse(2, [], []) == Vectors.dense([0.0, 0.0])


def test_row_access():
    r = Row(a=1, b="x")
    assert r["a"] == 1 and r.b == "x" and "a" in r
    assert r.asDict() == {"a": 1, "b": "x"}
    with pytest.raises(KeyError):
        r["zzz"]


def test_dataframe_ops():
    spark = LocalSession.builder.master("local[3]").getOrCreate()
    df = spark.createDataFrame([(i, float(i) * 2) for i in range(10)], ["a", "b"])
    assert df.count() == 10 and df.columns == ["a", "b"]
    sel = df.select("b")
    assert sel.columns == ["b"]
    assert df.rdd.getNumPartitions() == 3
    assert df.coalesce(1).rdd.getNumPartitions() == 1
    mapped = df.rdd.map(lambda r: r["a"] + 1).collect()
    assert mapped == list(range(1, 11))
    parts = []
    df.rdd.foreachPartition(lambda it: parts.append(len(list(it))))
    assert sum(parts) == 10 and len(parts) == 3


def test_feature_transformers():
    spark = LocalSession.builder.getOrCreate()
    df = spark.createDataFrame([(1.0, 2.0, 0.0), (3.0, 4.0, 2.0)],
                               ["f1", "f2", "cat"])
    va = VectorAssembler(inputCols=["f1", "f2"], outputCol="features")
    out = va.transform(df)
    np.testing.assert_allclose(out.first()["features"].toArray(), [1.0, 2.0])

    ohe = OneHotEncoder(inputCol="cat", outputCol="oh", dropLast=False)
    out2 = ohe.transform(df)
    np.testing.assert_allclose(out2.collect()[1]["oh"].toArray(), [0, 0, 1])
    # dropLast=True drops the final category (encoded all-zero)
    ohe2 = OneHotEncoder(inputCol="cat", outputCol="oh")
    np.testing.assert_allclose(ohe2.transform(df).collect()[1]["oh"].toArray(),
                               [0, 0])

    nz = Normalizer(inputCol="features", outputCol="norm", p=1.0)
    np.testing.assert_allclose(nz.transform(out).first()["norm"].toArray(),
                               [1 / 3, 2 / 3])


def test_evaluator_accuracy_and_f1():
    spark = LocalSession.builder.getOrCreate()
    df = spark.createDataFrame(
        [(1.0, 1.0), (0.0, 0.0), (1.0, 0.0), (1.0, 1.0)], ["label", "pred"])
    ev = MulticlassClassificationEvaluator(labelCol="label", predictionCol="pred",
                                           metricName="accuracy")
    assert ev.evaluate(df) == 0.75
    f1 = MulticlassClassificationEvaluator(labelCol="label", predictionCol="pred",
                                           metricName="f1").evaluate(df)
    assert 0.0 < f1 <= 1.0


def test_csv_reader(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2.5,hello\n3,4.5,world\n")
    spark = LocalSession.builder.getOrCreate()
    df = spark.read.option("inferSchema", "true").csv(str(p))
    rows = df.collect()
    assert rows[0]["_c0"] == 1 and rows[0]["_c1"] == 2.5 and rows[0]["_c2"] == "hello"


def test_rwlock_writer_priority_and_exclusion():
    lock = RWLock()
    log = []

    def reader(i):
        with lock.reading():
            log.append(("r", i))
            time.sleep(0.05)

    def writer():
        with lock.writing():
            log.append(("w", 0))

    with lock.reading():
        w = threading.Thread(target=writer)
        w.start()
        time.sleep(0.05)
        # a late reader must queue behind the waiting writer
        r = threading.Thread(target=reader, args=(99,))
        r.start()
        time.sleep(0.05)
        assert log == []  # nobody got in while we hold the read lock... writer waits
    w.join(2)
    r.join(2)
    assert log[0] == ("w", 0)  # writer won despite the queued reader


def test_rwlock_release_any_side():
    lock = RWLock()
    lock.acquire_read()
    lock.release()
    lock.acquire_write()
    lock.release()
    with pytest.raises(RuntimeError):
        lock.release()
