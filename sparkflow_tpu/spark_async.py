"""``SparkAsyncDL`` / ``SparkAsyncDLModel``: the Spark ML estimator surface.

Drop-in equivalents of the reference's public classes
(``sparkflow/tensorflow_async.py:51-321``) with the identical Param surface —
18 params on the estimator, 6 on the model — and ``.fit``/``.transform``
semantics, including unsupervised mode (``tfLabel=None``), the dropout feed
(``tfDropout``/``toKeepDropout``), and scalar-vs-vector prediction columns.

What changed underneath (the TPU-native part): ``_fit`` no longer spawns a Flask
parameter server and ship-pickles weights per batch — it stages the dataset onto
the local device mesh and runs whole-epoch compiled programs with gradient
all-reduce over ICI (see :mod:`sparkflow_tpu.trainer`). ``acquireLock``,
``port`` are accepted for API compatibility: lock-free vs locked updates have no
meaning under synchronous all-reduce, and there is no server to bind a port for.

Also importable as :mod:`sparkflow_tpu.tensorflow_async` for line-for-line
import compatibility with reference user code.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

import numpy as np

from .compat import (Estimator, HasInputCol, HasLabelCol, HasPredictionCol,
                     Identifiable, MLReadable, MLWritable, Model, Param, Params,
                     TypeConverters, keyword_only)
from .graphdef import GraphModel
from .localml.linalg import vector_to_array
from .ml_util import (convert_weights_to_json, handle_features, predict_func)
from .optimizers import build_optimizer_from_json
from .parallel.mesh import default_mesh, make_mesh
from .pipeline_util import PysparkReaderWriter
from .trainer import Trainer

logger = logging.getLogger("sparkflow_tpu")


def _split_csv(s: Optional[str]) -> list:
    """Comma-separated Param -> list of names (empty list for None/blank)."""
    return [t.strip() for t in (s or "").split(",") if t.strip()]


def _opt_param(obj, param, default=None):
    """getOrDefault that tolerates instances persisted by OLDER versions:
    dill-loaded stages restore the old _defaultParamMap, which lacks Params
    added since — treat those as their current default instead of KeyError."""
    try:
        return obj.getOrDefault(param)
    except KeyError:
        return default


def build_optimizer(optimizer_name, learning_rate, optimizer_options=None):
    """Name -> optax transformation (reference ``tensorflow_async.py:17-42``)."""
    from .optimizers import build_optimizer as _bo
    return _bo(optimizer_name, learning_rate, optimizer_options)


def handle_data(data, inp_col: str, label_col: Optional[str],
                extra_cols: Optional[list] = None):
    """Row -> (features ndarray, label) or bare features when unsupervised
    (reference ``tensorflow_async.py:45-48``). With ``extra_cols`` the
    features become a tuple (multi-input models)."""
    def feat(row):
        base = np.asarray(vector_to_array(row[inp_col]), dtype=np.float32)
        if extra_cols:
            return (base,) + tuple(
                np.asarray(vector_to_array(row[c]), dtype=np.float32)
                for c in extra_cols)
        return base

    if label_col is None:
        return feat(data)
    return (feat(data), data[label_col])


class SparkAsyncDLModel(Model, HasInputCol, HasPredictionCol, PysparkReaderWriter,
                        MLReadable, MLWritable, Identifiable):
    """Fitted model: graph JSON + weights JSON as string Params, applied
    per-partition (reference ``tensorflow_async.py:51-99``)."""

    modelJson = Param(Params._dummy(), "modelJson", "", typeConverter=TypeConverters.toString)
    modelWeights = Param(Params._dummy(), "modelWeights", "", typeConverter=TypeConverters.toString)
    tfOutput = Param(Params._dummy(), "tfOutput", "", typeConverter=TypeConverters.toString)
    tfInput = Param(Params._dummy(), "tfInput", "", typeConverter=TypeConverters.toString)
    tfDropout = Param(Params._dummy(), "tfDropout", "", typeConverter=TypeConverters.toString)
    toKeepDropout = Param(Params._dummy(), "toKeepDropout", "", typeConverter=TypeConverters.toBoolean)
    # upgrade: extra (column, tensor) feeds for multi-input models, e.g. an
    # attention mask next to token ids; comma-separated so the Params stay
    # plain strings (persistence-friendly, like every reference Param)
    extraInputCols = Param(Params._dummy(), "extraInputCols", "", typeConverter=TypeConverters.toString)
    extraTfInputs = Param(Params._dummy(), "extraTfInputs", "", typeConverter=TypeConverters.toString)
    # upgrade: int8-quantized serving ('' = off, 'weight_only', 'dynamic');
    # weights stay full-precision in the persisted Params — quantization
    # happens executor-side at serve time (utils/quant.py)
    inferenceQuantize = Param(Params._dummy(), "inferenceQuantize", "", typeConverter=TypeConverters.toString)
    # upgrade: serve over a device mesh ("dp=8"): the batch shards over dp
    # (data-parallel inference only — params serve replicated); unset ->
    # single default device (reference-shaped executor-local inference)
    meshShape = Param(Params._dummy(), "meshShape", "", typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self,
                 inputCol=None,
                 modelJson=None,
                 modelWeights=None,
                 tfInput=None,
                 tfOutput=None,
                 tfDropout=None,
                 toKeepDropout=None,
                 predictionCol=None,
                 extraInputCols=None,
                 extraTfInputs=None,
                 inferenceQuantize=None,
                 meshShape=None):
        super(SparkAsyncDLModel, self).__init__()
        self._setDefault(modelJson=None, inputCol='encoded',
                         predictionCol='predicted', tfOutput=None, tfInput=None,
                         modelWeights=None, tfDropout=None, toKeepDropout=False,
                         extraInputCols=None, extraTfInputs=None,
                         inferenceQuantize=None, meshShape=None)
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self,
                  inputCol=None,
                  modelJson=None,
                  modelWeights=None,
                  tfInput=None,
                  tfOutput=None,
                  tfDropout=None,
                  toKeepDropout=None,
                  predictionCol=None,
                  extraInputCols=None,
                  extraTfInputs=None,
                  inferenceQuantize=None,
                  meshShape=None):
        kwargs = self._input_kwargs
        return self._set(**kwargs)

    def _transform(self, dataset):
        inp = self.getOrDefault(self.inputCol)
        out = self.getOrDefault(self.predictionCol)
        mod_json = self.getOrDefault(self.modelJson)
        mod_weights = self.getOrDefault(self.modelWeights)
        tf_input = self.getOrDefault(self.tfInput)
        tf_output = self.getOrDefault(self.tfOutput)
        tf_dropout = self.getOrDefault(self.tfDropout)
        to_keep_dropout = self.getOrDefault(self.toKeepDropout)
        extra_cols = _split_csv(_opt_param(self, self.extraInputCols))
        extra_inputs = _split_csv(_opt_param(self, self.extraTfInputs))
        if len(extra_cols) != len(extra_inputs):
            raise ValueError(
                "extraInputCols (%d names) and extraTfInputs (%d names) must "
                "pair up one-to-one" % (len(extra_cols), len(extra_inputs)))
        quantize = _opt_param(self, self.inferenceQuantize) or None
        if quantize:
            from .utils.quant import MODES
            if quantize not in MODES:
                raise ValueError(
                    "inferenceQuantize must be one of %s (or unset), got %r"
                    % (list(MODES), quantize))
        mesh_shape = _opt_param(self, self.meshShape) or None
        if mesh_shape:
            from .parallel.mesh import parse_mesh_shape
            mesh_axes = parse_mesh_shape(mesh_shape)
            bad = [a_ for a_ in mesh_axes if a_ != "dp"]
            if bad:
                # inference shards the BATCH; params serve replicated, so a
                # tp/fsdp/... axis would silently replicate compute instead
                # of parallelizing it — refuse rather than mislead
                raise ValueError(
                    "Model meshShape serves data-parallel only ('dp=N'); "
                    "axes %s are not inference strategies" % bad)
            import jax as _jax
            need = int(np.prod(list(mesh_axes.values())))
            have = len(_jax.devices())
            if need > have:
                # fail on the DRIVER with a clear message, not as an opaque
                # task failure inside mapPartitions at action time
                raise ValueError(
                    "Model meshShape %r needs %d devices; %d visible"
                    % (mesh_shape, need, have))
        else:
            mesh_axes = None
        return dataset.rdd.mapPartitions(
            lambda x: predict_func(x, mod_json, out, mod_weights, inp, tf_output,
                                   tf_input, tf_dropout, to_keep_dropout,
                                   extra_cols=extra_cols or None,
                                   extra_inputs=extra_inputs or None,
                                   quantize=quantize,
                                   mesh_axes=mesh_axes)).toDF()


class SparkAsyncDL(Estimator, HasInputCol, HasPredictionCol, HasLabelCol,
                   PysparkReaderWriter, MLReadable, MLWritable, Identifiable):
    """Estimator with the reference's full 18-Param surface
    (``tensorflow_async.py:102-210``); ``_fit`` trains on the TPU mesh."""

    tensorflowGraph = Param(Params._dummy(), "tensorflowGraph", "", typeConverter=TypeConverters.toString)
    tfInput = Param(Params._dummy(), "tfInput", "", typeConverter=TypeConverters.toString)
    tfOutput = Param(Params._dummy(), "tfOutput", "", typeConverter=TypeConverters.toString)
    tfLabel = Param(Params._dummy(), "tfLabel", "", typeConverter=TypeConverters.toString)
    tfOptimizer = Param(Params._dummy(), "tfOptimizer", "", typeConverter=TypeConverters.toString)
    tfLearningRate = Param(Params._dummy(), "tfLearningRate", "", typeConverter=TypeConverters.toFloat)
    iters = Param(Params._dummy(), "iters", "", typeConverter=TypeConverters.toInt)
    partitions = Param(Params._dummy(), "partitions", "", typeConverter=TypeConverters.toInt)
    miniBatchSize = Param(Params._dummy(), "miniBatchSize", "", typeConverter=TypeConverters.toInt)
    miniStochasticIters = Param(Params._dummy(), "miniStochasticIters", "", typeConverter=TypeConverters.toInt)
    verbose = Param(Params._dummy(), "verbose", "", typeConverter=TypeConverters.toInt)
    acquireLock = Param(Params._dummy(), "acquireLock", "", typeConverter=TypeConverters.toBoolean)
    shufflePerIter = Param(Params._dummy(), "shufflePerIter", "", typeConverter=TypeConverters.toBoolean)
    tfDropout = Param(Params._dummy(), "tfDropout", "", typeConverter=TypeConverters.toString)
    toKeepDropout = Param(Params._dummy(), "toKeepDropout", "", typeConverter=TypeConverters.toBoolean)
    partitionShuffles = Param(Params._dummy(), "partitionShuffles", "", typeConverter=TypeConverters.toInt)
    optimizerOptions = Param(Params._dummy(), "optimizerOptions", "", typeConverter=TypeConverters.toString)
    port = Param(Params._dummy(), "port", "", typeConverter=TypeConverters.toInt)
    # upgrades over the reference param set (defaults preserve its behavior):
    # weightsPath: store trained weights in an npz side-file instead of inline
    # JSON (the reference's whole-weights-in-pipeline-metadata becomes
    # impractical for ResNet/BERT-scale models — SURVEY.md anti-features);
    # checkpointDir/checkpointEvery: mid-training checkpoint + resume.
    weightsPath = Param(Params._dummy(), "weightsPath", "", typeConverter=TypeConverters.toString)
    checkpointDir = Param(Params._dummy(), "checkpointDir", "", typeConverter=TypeConverters.toString)
    checkpointEvery = Param(Params._dummy(), "checkpointEvery", "", typeConverter=TypeConverters.toInt)
    # fitMode: 'collect' (reference behavior, tensorflow_async.py:290-293 —
    # materialize the RDD on the driver) or 'stream' (rdd.toLocalIterator into
    # Trainer.fit_stream: the dataset is consumed one partition at a time and
    # never fully materializes on the driver — SURVEY.md hard-part #1). In
    # stream mode the `partitions` Param is the streaming granularity: one
    # partition is the most data resident on the driver at once.
    fitMode = Param(Params._dummy(), "fitMode", "", typeConverter=TypeConverters.toString)
    # extra (column, tensor) feeds for multi-input models (see the Model)
    extraInputCols = Param(Params._dummy(), "extraInputCols", "", typeConverter=TypeConverters.toString)
    extraTfInputs = Param(Params._dummy(), "extraTfInputs", "", typeConverter=TypeConverters.toString)
    # upgrade: device-mesh shape as a plain string ("dp=2,tp=4",
    # "dp=2,fsdp=4", ...) so multi-strategy parallelism is reachable from the
    # Param surface; unset -> all local devices on one 'dp' axis
    meshShape = Param(Params._dummy(), "meshShape", "", typeConverter=TypeConverters.toString)
    # upgrade: the fitted model stores the Polyak-averaged weights instead of
    # the raw final ones; requires {'ema_decay': d} in optimizerOptions
    useEmaWeights = Param(Params._dummy(), "useEmaWeights", "", typeConverter=TypeConverters.toBoolean)
    # upgrades: pipeline-parallel knobs for meshShape='...,pp=N' fits —
    # microbatches per batch (-1 = deepest power of two the per-replica
    # batch divides) and schedule ('gpipe' | '1f1b' | 'sequential')
    ppMicrobatches = Param(Params._dummy(), "ppMicrobatches", "", typeConverter=TypeConverters.toInt)
    ppSchedule = Param(Params._dummy(), "ppSchedule", "", typeConverter=TypeConverters.toString)
    # upgrade: ZeRO-1 weight-update sharding on pure-dp meshes ('auto' |
    # 'on' | 'off'): reduce-scatter gradients, run the optimizer on a 1/dp
    # shard of params+state, all-gather the updated params — ~1/dp
    # optimizer-state memory per device, same collective bytes. 'auto' turns
    # on when the optimizer carries per-param state and dp >= 2.
    weightUpdateSharding = Param(Params._dummy(), "weightUpdateSharding", "", typeConverter=TypeConverters.toString)
    # upgrade: explicit ZeRO stage (0-3) mapped through as_sharding_config
    # into the Trainer's declarative ShardingConfig; -1 (default) leaves the
    # legacy weightUpdateSharding semantics in charge. Unlike 'auto', a set
    # stage is a REQUEST — ineligible fits raise instead of falling back.
    zeroStage = Param(Params._dummy(), "zeroStage", "", typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self,
                 inputCol=None,
                 tensorflowGraph=None,
                 tfInput=None,
                 tfLabel=None,
                 tfOutput=None,
                 tfOptimizer=None,
                 tfLearningRate=None,
                 iters=None,
                 predictionCol=None,
                 partitions=None,
                 miniBatchSize=None,
                 miniStochasticIters=None,
                 acquireLock=None,
                 shufflePerIter=None,
                 tfDropout=None,
                 toKeepDropout=None,
                 verbose=None,
                 labelCol=None,
                 partitionShuffles=None,
                 optimizerOptions=None,
                 port=None,
                 weightsPath=None,
                 checkpointDir=None,
                 checkpointEvery=None,
                 fitMode=None,
                 extraInputCols=None,
                 extraTfInputs=None,
                 meshShape=None,
                 useEmaWeights=None,
                 ppMicrobatches=None,
                 ppSchedule=None,
                 weightUpdateSharding=None,
                 zeroStage=None):
        """Same parameter meanings as the reference estimator docstring
        (``tensorflow_async.py:146-175``); ``acquireLock`` and ``port`` are
        accepted no-ops under synchronous all-reduce training. ``weightsPath``,
        ``checkpointDir``/``checkpointEvery`` are upgrades (side-file weights,
        mid-training checkpoint+resume)."""
        super(SparkAsyncDL, self).__init__()
        self._setDefault(inputCol='transformed', tensorflowGraph='',
                         tfInput='x:0', tfLabel=None, tfOutput='out/Sigmoid:0',
                         tfOptimizer='adam', tfLearningRate=.01, partitions=5,
                         miniBatchSize=128, miniStochasticIters=-1,
                         shufflePerIter=True, tfDropout=None, acquireLock=False,
                         verbose=0, iters=1000, toKeepDropout=False,
                         predictionCol='predicted', labelCol=None,
                         partitionShuffles=1, optimizerOptions=None, port=5000,
                         weightsPath=None, checkpointDir=None, checkpointEvery=0,
                         fitMode='collect', extraInputCols=None,
                         extraTfInputs=None, meshShape=None,
                         useEmaWeights=False, ppMicrobatches=-1,
                         ppSchedule='gpipe', weightUpdateSharding='auto',
                         zeroStage=-1)
        self._loss_callback = None
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self,
                  inputCol=None,
                  tensorflowGraph=None,
                  tfInput=None,
                  tfLabel=None,
                  tfOutput=None,
                  tfOptimizer=None,
                  tfLearningRate=None,
                  iters=None,
                  predictionCol=None,
                  partitions=None,
                  miniBatchSize=None,
                  miniStochasticIters=None,
                  acquireLock=None,
                  shufflePerIter=None,
                  tfDropout=None,
                  toKeepDropout=None,
                  verbose=None,
                  labelCol=None,
                  partitionShuffles=None,
                  optimizerOptions=None,
                  port=None,
                  weightsPath=None,
                  checkpointDir=None,
                  checkpointEvery=None,
                  fitMode=None,
                  extraInputCols=None,
                  extraTfInputs=None,
                  meshShape=None,
                 useEmaWeights=None,
                 ppMicrobatches=None,
                 ppSchedule=None,
                 weightUpdateSharding=None,
                 zeroStage=None):
        kwargs = self._input_kwargs
        return self._set(**kwargs)

    def setLossCallback(self, fn):
        """Per-iteration ``fn(loss, iteration, partition_id)`` hook — the hook
        the reference declared on HogwildSparkModel but never plumbed through
        the estimator (``HogwildSparkModel.py:124``; SURVEY.md §5). Not a
        Param (functions don't persist); set it per-fit."""
        self._loss_callback = fn
        return self

    # getters (reference tensorflow_async.py:212-264)
    def getTensorflowGraph(self):
        return self.getOrDefault(self.tensorflowGraph)

    def getIters(self):
        return self.getOrDefault(self.iters)

    def getTfInput(self):
        return self.getOrDefault(self.tfInput)

    def getTfLabel(self):
        return self.getOrDefault(self.tfLabel)

    def getTfOutput(self):
        return self.getOrDefault(self.tfOutput)

    def getTfOptimizer(self):
        return self.getOrDefault(self.tfOptimizer)

    def getTfLearningRate(self):
        return self.getOrDefault(self.tfLearningRate)

    def getPartitions(self):
        return self.getOrDefault(self.partitions)

    def getMiniBatchSize(self):
        return self.getOrDefault(self.miniBatchSize)

    def getMiniStochasticIters(self):
        return self.getOrDefault(self.miniStochasticIters)

    def getVerbose(self):
        return self.getOrDefault(self.verbose)

    def getAcquireLock(self):
        return self.getOrDefault(self.acquireLock)

    def getShufflePerIter(self):
        return self.getOrDefault(self.shufflePerIter)

    def getTfDropout(self):
        return self.getOrDefault(self.tfDropout)

    def getToKeepDropout(self):
        return self.getOrDefault(self.toKeepDropout)

    def getPartitionShuffles(self):
        return self.getOrDefault(self.partitionShuffles)

    def getOptimizerOptions(self):
        return self.getOrDefault(self.optimizerOptions)

    def getPort(self):
        return self.getOrDefault(self.port)

    def getMeshShape(self):
        return _opt_param(self, self.meshShape)

    def getFitMode(self):
        return _opt_param(self, self.fitMode, "collect")

    def _validate_params(self):
        """Error loudly on inconsistent Param combinations — the reference
        fails fast when a supervised graph gets no label
        (``tensorflow_async.py:290`` KeyErrors on the missing column); silently
        training a supervised loss against dummy zeros is worse."""
        label_col = self.getOrDefault(self.labelCol)
        tf_label = self.getTfLabel()
        if tf_label is not None and label_col is None:
            raise ValueError(
                "tfLabel=%r names a label tensor but labelCol is None: the "
                "supervised loss would train on dummy zero labels. Set "
                "labelCol (or clear tfLabel for unsupervised training)."
                % tf_label)
        if label_col is not None and tf_label is None:
            raise ValueError(
                "labelCol=%r supplies labels but tfLabel is None, so no loss "
                "consumes them. Set tfLabel (or clear labelCol)." % label_col)
        fit_mode = (self.getFitMode() or "collect").lower()
        if fit_mode not in ("collect", "stream"):
            raise ValueError("fitMode must be 'collect' or 'stream', got %r"
                             % self.getFitMode())
        extra_cols = _split_csv(_opt_param(self, self.extraInputCols))
        extra_inputs = _split_csv(_opt_param(self, self.extraTfInputs))
        if len(extra_cols) != len(extra_inputs):
            raise ValueError(
                "extraInputCols (%d names) and extraTfInputs (%d names) must "
                "pair up one-to-one" % (len(extra_cols), len(extra_inputs)))
        mesh_axes = None
        mesh_shape = self.getMeshShape()
        if mesh_shape:
            from .parallel.mesh import parse_mesh_shape
            mesh_axes = parse_mesh_shape(mesh_shape)  # raises on bad syntax
            if (("sp" in mesh_axes or "pp" in mesh_axes)
                    and fit_mode == "stream"):
                raise ValueError(
                    "meshShape axes sp/pp need fitMode='collect': their "
                    "fixed-shape batch schedules stage the whole dataset "
                    "(the Trainer refuses pp/sp in fit_stream)")
            if "dp" not in mesh_axes:
                # the compiled epochs shard dataset rows over 'dp'; a size-1
                # axis makes e.g. "fsdp=8" mean "all devices shard params,
                # none shard data" instead of a deep GSPMD error
                mesh_axes = {"dp": 1, **mesh_axes}
        sched = _opt_param(self, self.ppSchedule, "gpipe") or "gpipe"
        if sched not in ("gpipe", "1f1b", "sequential"):
            raise ValueError(
                "ppSchedule must be 'gpipe', '1f1b', or 'sequential'; got %r"
                % sched)
        wus = _opt_param(self, self.weightUpdateSharding, "auto") or "auto"
        if wus not in ("auto", "on", "off"):
            raise ValueError(
                "weightUpdateSharding must be 'auto', 'on', or 'off'; got %r"
                % wus)
        zs = _opt_param(self, self.zeroStage, -1)
        zs = -1 if zs is None else int(zs)
        if zs not in (-1, 0, 1, 2, 3):
            raise ValueError(
                "zeroStage must be -1 (unset) or 0-3; got %r" % zs)
        if self.getOrDefault(self.useEmaWeights):
            # fail BEFORE training, not after hours of fit: the EMA only
            # exists when the optimizer maintains it (build_optimizer
            # validates the range, incl. sign typos, also pre-fit)
            raw = self.getOptimizerOptions()
            opts_d = (json.loads(raw) if isinstance(raw, str) and raw
                      else (raw or {}))
            d = float(opts_d.get("ema_decay", 0) or 0)
            if not 0.0 < d < 1.0:
                raise ValueError(
                    "useEmaWeights=True requires {'ema_decay': d} with "
                    "0 < d < 1 in optimizerOptions — no EMA would be "
                    "maintained (got %r)" % d)
        # Documented no-ops (there is no parameter server): warn so a config
        # carried over from the reference states its own inertness instead of
        # silently passing (tests assert these warnings — the API contract is
        # "accepted, warned, ignored").
        if self.getAcquireLock():
            logger.warning(
                "acquireLock=True has no effect: synchronous all-reduce "
                "updates are already serialized (no Hogwild parameter server "
                "exists to lock)")
        if self.isSet(self.port):
            logger.warning(
                "port=%d has no effect: there is no parameter server to bind "
                "a port for (weights never leave the device mesh)",
                self.getPort())
        return fit_mode, extra_cols, extra_inputs, mesh_axes

    def _sharding_config(self):
        """``zeroStage`` >= 0 mapped into a declarative
        :class:`~sparkflow_tpu.sharding.ShardingConfig`; ``-1`` (unset)
        returns ``None`` so the legacy ``weightUpdateSharding`` knob keeps
        driving the trainer's eligibility gate."""
        stage = _opt_param(self, self.zeroStage, -1)
        stage = -1 if stage is None else int(stage)
        if stage < 0:
            return None
        from .sharding import as_sharding_config
        return as_sharding_config({"zero_stage": stage})

    def _fit(self, dataset):
        inp_col = self.getOrDefault(self.inputCol)
        graph_json = self.getTensorflowGraph()
        label_col = self.getOrDefault(self.labelCol)
        tf_label = self.getTfLabel()
        optimizer_options = self.getOptimizerOptions()
        fit_mode, extra_cols, extra_inputs, mesh_axes = self._validate_params()

        # DataFrame -> (features, label) pairs; partitions Param shapes the RDD
        # exactly as the reference does (tensorflow_async.py:290-291). In
        # collect mode the union of partition data is staged onto the device
        # mesh; in stream mode partitions are consumed one at a time.
        rdd = dataset.rdd.map(
            lambda r: handle_data(r, inp_col, label_col,
                                  extra_cols=extra_cols or None))
        partitions = self.getPartitions()
        if rdd.getNumPartitions() > partitions:
            rdd = rdd.coalesce(partitions)

        optimizer = build_optimizer_from_json(self.getTfOptimizer(),
                                              self.getTfLearningRate(),
                                              optimizer_options)
        input_spec = ([self.getTfInput()] + extra_inputs if extra_inputs
                      else self.getTfInput())
        trainer = Trainer(
            graph_json,
            input_spec,
            tf_label,
            optimizer=optimizer,
            iters=self.getIters(),
            mini_batch_size=self.getMiniBatchSize(),
            mini_stochastic_iters=self.getMiniStochasticIters(),
            shuffle_per_iter=self.getShufflePerIter(),
            partition_shuffles=self.getPartitionShuffles(),
            verbose=self.getVerbose(),
            loss_callback=self._loss_callback,
            dropout_name=self.getTfDropout(),
            acquire_lock=self.getAcquireLock(),
            mesh=(make_mesh(mesh_axes) if mesh_axes else default_mesh()),
            checkpoint_dir=self.getOrDefault(self.checkpointDir),
            checkpoint_every=self.getOrDefault(self.checkpointEvery) or 0,
            pp_microbatches=(None if (_opt_param(self, self.ppMicrobatches,
                                                 -1) or -1) < 1
                             else _opt_param(self, self.ppMicrobatches)),
            pp_schedule=_opt_param(self, self.ppSchedule, "gpipe") or "gpipe",
            weight_update_sharding=(_opt_param(self, self.weightUpdateSharding,
                                               "auto") or "auto"),
            sharding=self._sharding_config(),
            # alongside the built optax object so the zero1 'auto' gate can
            # see clip_norm / ema_decay
            optimizer_options=(json.loads(optimizer_options)
                               if isinstance(optimizer_options, str)
                               and optimizer_options
                               else optimizer_options),
        )
        if fit_mode == "stream":
            # one epoch = one pass over rdd.toLocalIterator(): the dataset
            # never fully materializes on the driver (bounded by one
            # partition + the batch-assembly ring). Epoch count matches the
            # collect path (iters x partitionShuffles passes); optimizer
            # state and the rng stream persist across passes inside
            # fit_stream, exactly like epochs over an in-memory dataset.
            epochs = max(1, self.getIters()) * max(1, self.getPartitionShuffles())
            # executor-side persist: without it every epoch would re-execute
            # the full RDD lineage (driver memory stays bounded either way)
            if hasattr(rdd, "persist"):
                rdd.persist()
            try:
                result = trainer.fit_stream(rdd.toLocalIterator, epochs=epochs)
            finally:
                if hasattr(rdd, "unpersist"):
                    rdd.unpersist()
        else:
            items = rdd.collect()
            features, labels = handle_features(
                items, is_supervised=label_col is not None)
            result = trainer.fit(features, labels)
        final_weights = trainer.weights_list()
        if self.getOrDefault(self.useEmaWeights):
            ema = trainer.ema_weights()
            if ema is None:
                raise ValueError(
                    "useEmaWeights=True requires {'ema_decay': d} in "
                    "optimizerOptions (no EMA was maintained this fit)")
            from .graphdef import params_to_list
            final_weights = params_to_list(trainer.model, ema)
        weights_path = self.getOrDefault(self.weightsPath)
        if weights_path:
            if not weights_path.endswith(".npz"):
                weights_path += ".npz"
            from .model_loader import save_weights_npz
            save_weights_npz(weights_path, final_weights)
            # NOTE: the model stores this PATH, not the weights — unlike the
            # reference's self-contained inline JSON, the file must be visible
            # to every executor/machine that transforms or loads the pipeline
            # (use a shared filesystem path).
            logger.warning(
                "weightsPath=%s: model references a filesystem path; ensure it "
                "is reachable from all executors and travels with saved "
                "pipelines", weights_path)
            weights_json = "npz:" + weights_path
        else:
            weights_json = convert_weights_to_json(final_weights)

        return SparkAsyncDLModel(
            inputCol=inp_col,
            modelJson=graph_json,
            modelWeights=weights_json,
            tfOutput=self.getTfOutput(),
            tfInput=self.getTfInput(),
            tfDropout=self.getTfDropout(),
            toKeepDropout=self.getToKeepDropout(),
            predictionCol=self.getOrDefault(self.predictionCol),
            extraInputCols=_opt_param(self, self.extraInputCols),
            extraTfInputs=_opt_param(self, self.extraTfInputs))
