"""Data-parallel train step via shard_map: the whole-step manual-SPMD form.

``core.make_train_step``'s GSPMD jit now keeps the flash kernel too — its
trace runs under ``ops.attention.sharded_attention``, which nests a
shard_map around just the attention op. This module is the WHOLE-STEP
shard_map form: every operand is the device-LOCAL shard end to end, so all
pallas kernels run per-device with no partitioner involved anywhere — the
standard recipe for custom kernels on a mesh (scaling-book §sharding: map
the kernel, let the collectives handle the rest).

Semantics are identical to the GSPMD step: the loss is the global masked
mean, gradients are ``psum``-reduced sums divided by the global example
count, and the optax update runs replicated (identical on every device).
Dropout rngs fold in the device index so shards draw independent masks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_dp_shardmap_train_step(model, optimizer, mesh: Mesh,
                                input_name, label_name: Optional[str],
                                dp_axis: str = "dp",
                                dcn_axis: Optional[str] = None):
    """Jitted train step with the model body under shard_map over ``dp_axis``.

    Signature matches ``core.make_train_step``'s:
    ``step(params, opt_state, x, y, mask, rng) -> (params, opt_state, loss)``
    with x/y/mask sharded over ``dp_axis`` (row counts must divide the axis
    size) and params/opt_state replicated.

    ``dcn_axis`` names a second, slower batch axis for multi-slice meshes
    (mesh ``{dcn: n_slices, dp: chips_per_slice}``): the batch shards over
    BOTH axes and the gradient merge becomes
    :func:`~sparkflow_tpu.parallel.collectives.hierarchical_psum_mean` —
    reduce_scatter inside each slice over ICI, a 1/n_ici-sized all-reduce
    across slices over DCN, all_gather back. Numerics are identical to the
    flat psum; the cross-slice wire traffic drops by the ICI axis size.
    """
    from ..core import make_feeds_builder
    from .collectives import hierarchical_psum_mean
    build_feeds = make_feeds_builder(input_name, label_name)
    if dcn_axis is not None and dcn_axis not in mesh.axis_names:
        # silently downgrading a typo'd axis would replicate the batch over
        # the real dcn axis (redundant identical updates per slice)
        raise ValueError(
            f"dcn_axis={dcn_axis!r} is not a mesh axis "
            f"{list(mesh.axis_names)}")
    two_level = dcn_axis is not None
    axes = (dcn_axis, dp_axis) if two_level else (dp_axis,)
    data_spec = P(axes if two_level else dp_axis)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), P(), data_spec, data_spec, data_spec, P()),
             out_specs=(P(), P(), P()),
             check_vma=False)
    def step(params, opt_state, x, y, mask, rng):
        for a in axes:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(a))

        def local_sum(p):
            lv = model.loss_vector(p, build_feeds(x, y), train=True, rng=rng)
            return jnp.sum(lv * mask)

        s, grads = jax.value_and_grad(local_sum)(params)
        n = jnp.maximum(jax.lax.psum(jnp.sum(mask), axes), 1.0)
        loss = jax.lax.psum(s, axes) / n
        if two_level:
            # sum-reduce hierarchically, then rescale mean-by-count: the
            # helper divides by the device count, the loss divides by the
            # (psummable) example count
            total = jax.lax.psum(1, axes)
            grads = jax.tree.map(
                lambda g: g * (total / n),
                hierarchical_psum_mean(grads, ici_axis=dp_axis,
                                       dcn_axis=dcn_axis))
        else:
            grads = jax.tree.map(lambda g: jax.lax.psum(g, dp_axis) / n,
                                 grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
