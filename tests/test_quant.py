"""int8 quantized serving (utils/quant.py + graphdef integration).

The reference serves f32 through tf.Session (sparkflow/ml_util.py:65-73);
quantized serving is a TPU-era capability upgrade: same predict surface,
int8 weights. These tests pin the numerics contract (quantized predictions
track full-precision ones) and the estimator-level wiring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.graphdef import GraphModel
from sparkflow_tpu.trainer import Trainer
from sparkflow_tpu.core import make_predict_fn, predict_in_chunks
from sparkflow_tpu.utils.quant import (dequantize_tensor, int8_matmul,
                                       quantize_params, quantize_tensor)


def _mlp():
    x = nn.placeholder([None, 32], name="x")
    y = nn.placeholder([None, 4], name="y")
    h = nn.dense(x, 64, activation="relu")
    out = nn.dense(h, 4, name="out")
    nn.softmax_cross_entropy(y, out)


def _cnn():
    x = nn.placeholder([None, 64], name="x")
    y = nn.placeholder([None, 3], name="y")
    xr = nn.reshape(x, [-1, 8, 8, 1])
    c = nn.conv2d(xr, 16, 3, activation="relu")
    out = nn.dense(nn.flatten(c), 3, name="out")
    nn.softmax_cross_entropy(y, out)


def test_quantize_tensor_roundtrip_error_bound():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(64, 32) * 0.3, jnp.float32)
    q8, scale = quantize_tensor(w, axis=-1)
    assert q8.dtype == jnp.int8
    deq = dequantize_tensor(q8, scale)
    # symmetric rounding: error <= scale/2 elementwise, per output channel
    err = np.abs(np.asarray(deq) - np.asarray(w))
    assert np.all(err <= np.asarray(scale)[0] / 2 + 1e-7)
    # zero column stays exactly zero with a benign scale
    wz = w.at[:, 3].set(0.0)
    q8z, sz = quantize_tensor(wz, axis=-1)
    assert float(jnp.max(jnp.abs(dequantize_tensor(q8z, sz)[:, 3]))) == 0.0


def test_int8_matmul_tracks_f32():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(16, 48), jnp.float32)
    w = jnp.asarray(rs.randn(48, 24) * 0.2, jnp.float32)
    q8, scale = quantize_tensor(w, axis=-1)
    ref = x @ w
    got = int8_matmul(x, q8, scale)
    # int8 x int8 with dynamic per-row activation scales: ~1% relative on
    # the matmul's output scale
    tol = 0.02 * float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(got - ref))) < tol


def test_quantize_params_selects_by_size_and_name():
    model = GraphModel.from_json(build_graph(_mlp))
    params = model.init(jax.random.PRNGKey(0))
    q = quantize_params(params, min_size=1024)
    # 32x64 = 2048 quantizes; 64x4 = 256 stays full precision
    assert "kernel_q8" in q["dense/BiasAdd"] and "kernel" not in q["dense/BiasAdd"]
    assert q["dense/BiasAdd"]["kernel_q8"].dtype == jnp.int8
    assert "kernel" in q["out/BiasAdd"] and "kernel_q8" not in q["out/BiasAdd"]
    # biases untouched
    assert q["dense/BiasAdd"]["bias"].dtype == jnp.float32


@pytest.mark.parametrize("mode", ["weight_only", "dynamic"])
def test_graphmodel_quantized_predictions_track_f32(mode):
    rs = np.random.RandomState(2)
    x = rs.rand(256, 32).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 256)]
    tr = Trainer(build_graph(_mlp), "x:0", "y:0", iters=5, mini_batch_size=64)
    tr.fit(x, y)

    model = tr.model
    fp = np.asarray(predict_in_chunks(
        make_predict_fn(model, "x:0", "out:0"), tr.params, x))

    qparams = model.quantize_for_serving(tr.params, mode=mode, min_size=256)
    try:
        qp = np.asarray(predict_in_chunks(
            make_predict_fn(model, "x:0", "out:0"), qparams, x))
    finally:
        model.quant_mode = None
    # logits track within a small fraction of their dynamic range, and the
    # served class decisions overwhelmingly agree
    tol = 0.05 * (fp.max() - fp.min() + 1e-6)
    assert np.abs(qp - fp).max() < tol
    agree = (qp.argmax(axis=1) == fp.argmax(axis=1)).mean()
    # near-tie logits may legitimately flip under 8-bit rounding
    assert agree >= 0.98


def test_conv_kernel_quantizes_weight_only():
    rs = np.random.RandomState(3)
    x = rs.rand(64, 64).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    tr = Trainer(build_graph(_cnn), "x:0", "y:0", iters=2, mini_batch_size=32)
    tr.fit(x, y)
    model = tr.model
    fp = np.asarray(predict_in_chunks(
        make_predict_fn(model, "x:0", "out:0"), tr.params, x))
    qparams = model.quantize_for_serving(tr.params, mode="dynamic", min_size=64)
    try:
        assert "kernel_q8" in qparams[[k for k in qparams if k.startswith("conv2d")][0]]
        qp = np.asarray(predict_in_chunks(
            make_predict_fn(model, "x:0", "out:0"), qparams, x))
    finally:
        model.quant_mode = None
    tol = 0.05 * (fp.max() - fp.min() + 1e-6)
    assert np.abs(qp - fp).max() < tol


def test_quantize_for_serving_rejects_bad_mode():
    model = GraphModel.from_json(build_graph(_mlp))
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="weight_only"):
        model.quantize_for_serving(params, mode="int4")


def test_predict_func_rejects_bad_mode_and_non_graphdef_models():
    """Serving-side validation (predict_func is its own documented API):
    a typo'd mode must not silently serve a different path, and model types
    without a _q8 eval path must refuse rather than silently serve f32."""
    from sparkflow_tpu.ml_util import _cached_quantized_params
    from sparkflow_tpu.models import build_registry_spec, model_from_json

    gm = GraphModel.from_json(build_graph(_mlp))
    with pytest.raises(ValueError, match="weight_only"):
        _cached_quantized_params(gm, "[]", "dyanmic")  # typo

    reg = model_from_json(build_registry_spec(
        "rnn_classifier", vocab_size=50, num_classes=2, hidden=16,
        num_layers=1, max_len=8))
    with pytest.raises(ValueError, match="without quantization"):
        _cached_quantized_params(reg, "[]", "weight_only")
    with pytest.raises(ValueError, match="int8 serving"):
        reg.quantize_for_serving({}, mode="weight_only")


@pytest.mark.parametrize("mode", ["weight_only", "dynamic"])
def test_transformer_quantized_serving_tracks_f32(mode):
    """The flagship family serves int8: every block projection (qkv/o/fc1/
    fc2) consumes the quantized tree; class decisions track full precision."""
    from sparkflow_tpu.models import build_registry_spec, model_from_json

    m = model_from_json(build_registry_spec(
        "transformer_classifier", vocab_size=64, num_classes=4, hidden=32,
        num_layers=2, num_heads=4, mlp_dim=64, max_len=16, dropout=0.0))
    params = m.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(0, 64, (32, 16)), jnp.int32)

    fp = np.asarray(m.apply(params, {"input_ids": ids}, ["logits"])["logits"])
    qparams = m.quantize_for_serving(params, mode=mode, min_size=1024)
    try:
        # every block got its projections quantized
        assert "qkv_kernel_q8" in qparams["block_0"]
        assert "fc1_kernel_q8" in qparams["block_1"]
        qp = np.asarray(m.apply(qparams, {"input_ids": ids}, ["logits"])["logits"])
    finally:
        m.quant_mode = None
    tol = 0.06 * (fp.max() - fp.min() + 1e-6)
    assert np.abs(qp - fp).max() < tol
    agree = (qp.argmax(axis=1) == fp.argmax(axis=1)).mean()
    assert agree >= 0.95


def test_moe_transformer_quantized_serving():
    """MoE blocks quantize their attention projections; the expert banks
    (3-D) and router stay full precision."""
    from sparkflow_tpu.models import build_registry_spec, model_from_json

    m = model_from_json(build_registry_spec(
        "transformer_moe_lm", vocab_size=64, num_experts=4, moe_every=1,
        hidden=32, num_layers=2, num_heads=4, mlp_dim=64, max_len=16,
        dropout=0.0))
    params = m.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(0, 64, (8, 16)), jnp.int32)
    fp = np.asarray(m.apply(params, {"input_ids": ids}, ["logits"])["logits"])
    qparams = m.quantize_for_serving(params, min_size=1024)
    try:
        assert "qkv_kernel_q8" in qparams["block_0"]
        assert "experts_fc1" in qparams["block_0"]  # expert bank untouched
        assert qparams["block_0"]["router"].dtype == jnp.float32
        qp = np.asarray(m.apply(qparams, {"input_ids": ids}, ["logits"])["logits"])
    finally:
        m.quant_mode = None
    tol = 0.06 * (fp.max() - fp.min() + 1e-6)
    assert np.abs(qp - fp).max() < tol


def test_quantized_dense_respects_compute_dtype():
    """Weight-only serving on a bf16 model must run the matmul in bf16 —
    an f32 fallback would halve the MXU rate and double activation traffic."""
    from sparkflow_tpu.utils.quant import quantized_dense

    rs = np.random.RandomState(5)
    w = jnp.asarray(rs.randn(32, 16) * 0.2, jnp.float32)
    q8, scale = quantize_tensor(w)
    layer = {"kernel_q8": q8, "kernel_scale": scale}
    x = jnp.asarray(rs.randn(4, 32), jnp.bfloat16)
    y = quantized_dense(x, layer, "weight_only", compute_dtype=jnp.bfloat16)
    assert y.dtype == jnp.bfloat16


def test_quant_cache_sees_npz_rewrites(tmp_path):
    """npz side-file weights key the quantized-tree cache on (path, mtime,
    size): refitting and overwriting the same path must not serve the old
    quantized weights."""
    import time

    from sparkflow_tpu.ml_util import _cached_quantized_params
    from sparkflow_tpu.model_loader import save_weights_npz
    from sparkflow_tpu.graphdef import params_to_list

    model = GraphModel.from_json(build_graph(_mlp))
    p1 = model.init(jax.random.PRNGKey(0))
    p2 = model.init(jax.random.PRNGKey(1))
    path = str(tmp_path / "w.npz")

    save_weights_npz(path, params_to_list(model, p1))
    q1 = _cached_quantized_params(model, "npz:" + path, "weight_only")
    time.sleep(0.01)  # ensure mtime_ns differs across rewrites
    save_weights_npz(path, params_to_list(model, p2))
    q2 = _cached_quantized_params(model, "npz:" + path, "weight_only")
    d = max(float(jnp.max(jnp.abs(a.astype(jnp.float32) -
                                  b.astype(jnp.float32))))
            for a, b in zip(jax.tree.leaves(q1), jax.tree.leaves(q2)))
    assert d > 0.0, "cache served stale quantized weights after npz rewrite"


def test_estimator_inference_quantize_end_to_end():
    """inferenceQuantize Param: transform serves int8 with predictions
    tracking the f32 transform of the same fitted model."""
    from sparkflow_tpu.localml import LocalSession, Vectors
    from sparkflow_tpu.spark_async import SparkAsyncDL

    def model():
        x = nn.placeholder([None, 2], name="x")
        y = nn.placeholder([None, 1], name="y")
        h = nn.dense(x, 64, activation="relu")
        h = nn.dense(h, 64, activation="relu")  # 64x64: crosses min_size=4096
        out = nn.dense(h, 1, activation="sigmoid", name="outer")
        nn.sigmoid_cross_entropy(y, out)

    spark = LocalSession.builder.appName("quant-test").getOrCreate()
    rs = np.random.RandomState(4)
    rows = []
    for _ in range(100):
        rows.append((1.0, Vectors.dense(rs.normal(2, 1, 2))))
        rows.append((0.0, Vectors.dense(rs.normal(-2, 1, 2))))
    df = spark.createDataFrame(rows, ["label", "features"])

    est = SparkAsyncDL(
        inputCol="features", tensorflowGraph=build_graph(model),
        tfInput="x:0", tfLabel="y:0", tfOutput="outer/Sigmoid:0",
        labelCol="label", tfLearningRate=.1, iters=10, miniBatchSize=64,
        verbose=0)
    fitted = est.fit(df)

    base = [float(r["predicted"]) for r in fitted.transform(df).collect()]
    fitted.setParams(inferenceQuantize="weight_only")
    quant = [float(r["predicted"]) for r in fitted.transform(df).collect()]
    agree = np.mean([round(a) == round(b) for a, b in zip(base, quant)])
    assert agree >= 0.98
    assert np.max(np.abs(np.asarray(base) - np.asarray(quant))) < 0.05

    fitted.setParams(inferenceQuantize="int4")
    with pytest.raises(ValueError, match="inferenceQuantize"):
        fitted.transform(df)


def test_quantized_predict_on_dp_mesh():
    """Mesh-sharded inference serves quantized trees: the replicated-params
    jit shardings broadcast over the q8 tree unchanged."""
    from sparkflow_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")

    model = GraphModel.from_json(build_graph(_mlp))
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(8)
    x = rs.rand(100, 32).astype(np.float32)
    mesh = make_mesh({"dp": 8})

    fp = np.asarray(predict_in_chunks(
        make_predict_fn(model, "x:0", "out:0", mesh=mesh), params, x))
    q = model.quantize_for_serving(params, mode="dynamic", min_size=256)
    try:
        qp = np.asarray(predict_in_chunks(
            make_predict_fn(model, "x:0", "out:0", mesh=mesh), q, x))
    finally:
        model.quant_mode = None
    assert qp.shape == fp.shape
    assert np.abs(qp - fp).max() < 0.05 * (fp.max() - fp.min() + 1e-6)


def test_quantize_for_serving_warns_when_nothing_quantizes(caplog):
    """Unmatched naming / everything under min_size must not silently serve
    f32 while the caller believes it's int8."""
    import logging

    model = GraphModel.from_json(build_graph(_mlp))
    params = model.init(jax.random.PRNGKey(0))
    with caplog.at_level(logging.WARNING, logger="sparkflow_tpu.utils.quant"):
        model.quantize_for_serving(params, min_size=10**9)
    model.quant_mode = None
    assert any("FULL PRECISION" in r.message for r in caplog.records)
