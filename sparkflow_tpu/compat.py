"""Engine facade: real pyspark when importable, localml otherwise.

The estimator layer (``sparkflow_tpu.spark_async``) imports every Spark ML symbol
from here, so the same class definitions drop into a genuine
``pyspark.ml.Pipeline`` on a cluster (reference behavior,
``sparkflow/tensorflow_async.py:1-14``) or run standalone on
:mod:`sparkflow_tpu.localml` when pyspark isn't installed (e.g. this image).
``USING_PYSPARK`` tells persistence which wire path to use.
"""

from __future__ import annotations

try:  # covered by the pyspark CI job (make test-pyspark); absent locally
    from pyspark import keyword_only
    from pyspark.ml import Model
    from pyspark.ml.base import Estimator, Transformer
    from pyspark.ml.linalg import DenseVector, SparseVector, Vectors
    from pyspark.ml.param import Param, Params, TypeConverters
    from pyspark.ml.param.shared import (HasInputCol, HasLabelCol,
                                         HasPredictionCol)
    from pyspark.ml.pipeline import Pipeline, PipelineModel
    from pyspark.ml.util import Identifiable, MLReadable, MLWritable
    from pyspark.sql import Row

    USING_PYSPARK = True
except ImportError:
    from .localml.base import (Estimator, Identifiable, MLReadable,  # noqa: F401
                               MLWritable, Model, Transformer)
    from .localml.linalg import DenseVector, SparseVector, Vectors  # noqa: F401
    from .localml.param import (HasInputCol, HasLabelCol,  # noqa: F401
                                HasPredictionCol, Param, Params, TypeConverters,
                                keyword_only)
    from .localml.pipeline import Pipeline, PipelineModel  # noqa: F401
    from .localml.sql import Row  # noqa: F401

    USING_PYSPARK = False

__all__ = [
    "Estimator", "Transformer", "Model", "Identifiable", "MLReadable", "MLWritable",
    "Param", "Params", "TypeConverters", "keyword_only",
    "HasInputCol", "HasLabelCol", "HasPredictionCol",
    "Pipeline", "PipelineModel", "Vectors", "DenseVector", "SparseVector", "Row",
    "USING_PYSPARK",
]
