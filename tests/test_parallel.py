"""Pipeline (pp) and expert (ep) parallelism + distributed helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sparkflow_tpu.models import build_registry_spec, model_from_json
from sparkflow_tpu.optimizers import build_optimizer
from sparkflow_tpu.parallel.mesh import make_mesh, mesh_axis_size
from sparkflow_tpu.parallel.pp import (make_pp_train_step, merge_stage_params,
                                       pp_pspecs, split_stage_params)
from sparkflow_tpu.parallel.tp import filter_pspec, shard_params
from jax.sharding import PartitionSpec as P


@pytest.fixture(scope="module")
def pp_setup():
    spec = build_registry_spec("transformer_classifier", vocab_size=40,
                               num_classes=3, hidden=32, num_layers=8,
                               num_heads=4, mlp_dim=64, max_len=16, dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    return m, params


def test_stage_split_merge_roundtrip(pp_setup):
    m, params = pp_setup
    pp = split_stage_params(m, params, 4)
    back = merge_stage_params(m, pp)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_stage_split_copies_shared(pp_setup):
    m, params = pp_setup
    pp = split_stage_params(m, params, 4)
    # donation safety: shared leaves must not alias the caller's arrays
    assert pp["shared"]["embed"]["tok"] is not params["embed"]["tok"]


def test_pp_step_matches_single_device_and_trains(pp_setup):
    m, params = pp_setup
    mesh = make_mesh({"pp": 8})
    pp = shard_params(split_stage_params(m, params, 8), mesh,
                      pp_pspecs(split_stage_params(m, params, 8)))
    opt = build_optimizer("adam", 1e-3, None)
    state = opt.init(pp)
    step = make_pp_train_step(m, opt, mesh, n_microbatches=2)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 40, (8, 16)), jnp.int32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)
    pp, state, loss = step(pp, state, ids, y, jax.random.PRNGKey(1))
    ref = m.loss_vector(params, {"input_ids": ids, "y": y}, train=False).mean()
    np.testing.assert_allclose(float(loss), float(ref), atol=1e-4)
    first = float(loss)
    for i in range(6):
        pp, state, loss = step(pp, state, ids, y, jax.random.PRNGKey(i + 2))
    assert float(loss) < first


def test_pp_gpipe_matches_sequential_schedule(pp_setup):
    """The overlapped gpipe schedule must be a pure scheduling change: same
    loss and same updated params as the sequential baseline, with the serial
    span cut from M*P to M+P-1 stage-times."""
    m, params = pp_setup
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    opt = build_optimizer("gradient_descent", 0.1, None)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, 40, (8, 16)), jnp.int32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)

    results = {}
    for sched in ("gpipe", "1f1b", "sequential"):
        pp = shard_params(split_stage_params(m, params, 4), mesh,
                          pp_pspecs(split_stage_params(m, params, 4)))
        step = make_pp_train_step(m, opt, mesh, n_microbatches=4,
                                  schedule=sched)
        p2, _, loss = step(pp, opt.init(pp), ids, y, jax.random.PRNGKey(7))
        results[sched] = (float(loss), merge_stage_params(m, p2))

    for sched in ("gpipe", "1f1b"):
        assert results[sched][0] == pytest.approx(results["sequential"][0],
                                                  rel=1e-5), sched
        for a, b in zip(jax.tree.leaves(results[sched][1]),
                        jax.tree.leaves(results["sequential"][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=sched)
    # schedule property: 4 microbatches over 4 stages
    g = make_pp_train_step(m, opt, mesh, n_microbatches=4, schedule="gpipe")
    f = make_pp_train_step(m, opt, mesh, n_microbatches=4, schedule="1f1b")
    s = make_pp_train_step(m, opt, mesh, n_microbatches=4, schedule="sequential")
    assert g.schedule_ticks == 7 and s.schedule_ticks == 16
    # 1f1b table counts COMBINED fwd+bwd slots: ~2M + 2P - 3
    assert f.schedule_ticks == 14


def test_pp_1f1b_schedule_tables():
    """The simulated schedule has the canonical 1F1B shape: per-stage
    in-flight peaks at exactly min(M, P - s), every microbatch runs fwd+bwd
    exactly once per stage, and cotangents arrive on their consumption
    tick."""
    from sparkflow_tpu.parallel.pp import (_OP_BWD, _OP_FWD, _simulate_1f1b)

    for P, M in ((2, 2), (4, 4), (4, 8), (8, 16), (3, 5)):
        ops, mbs, arrf, arrm = _simulate_1f1b(P, M)
        for s in range(P):
            f = b = peak = 0
            for t in range(ops.shape[0]):
                if ops[t, s] == _OP_FWD:
                    f += 1
                if ops[t, s] == _OP_BWD:
                    b += 1
                peak = max(peak, f - b)
            # last stage's FWD ops are rewritten to NONE (arrival-stored)
            assert b == M, (P, M, s)
            if s < P - 1:
                assert f == M, (P, M, s)
                assert peak == min(M, P - s), (P, M, s, peak)


def test_moe_ep_sharding_matches_replicated():
    spec = build_registry_spec("transformer_moe_lm", vocab_size=40,
                               num_experts=8, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=16, dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 40, (4, 16)), jnp.int32)
    mesh = make_mesh({"ep": 8})
    sp = shard_params(params, mesh, m.param_pspecs())
    assert "ep" in str(sp["block_1"]["experts_fc1"].sharding.spec)

    def loss_fn(p):
        return m.loss_vector(p, {"input_ids": ids}, train=False).mean()

    np.testing.assert_allclose(float(loss_fn(params)),
                               float(jax.jit(loss_fn)(sp)), rtol=1e-5)


def test_moe_capacity_dispatch_matches_per_token_ffn():
    # with capacity >= tokens-per-expert nothing drops: routed output must
    # equal the per-token expert FFN times the gate, computed by hand
    spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                               num_experts=4, moe_every=1, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=8, dropout=0.0, capacity_factor=4.0)
    m = model_from_json(spec)
    bp = m.init(jax.random.PRNGKey(0))["block_0"]
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 8, 16), jnp.float32)
    y, aux = m._moe_mlp(bp, x)
    xf = np.asarray(x).reshape(-1, 16)
    logits = xf @ np.asarray(bp["router"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    idx = probs.argmax(-1)
    expect = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        e = idx[t]
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            xf[t] @ np.asarray(bp["experts_fc1"])[e] + np.asarray(bp["experts_b1"])[e])))
        expect[t] = (h @ np.asarray(bp["experts_fc2"])[e]
                     + np.asarray(bp["experts_b2"])[e]) * probs[t, e]
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), expect,
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0.0


def test_moe_capacity_dispatch_drops_overflow_tokens():
    spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                               num_experts=4, moe_every=1, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=8, dropout=0.0, capacity_factor=0.5)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    bp = dict(params["block_0"])
    # force every token onto expert 2 with positive inputs -> argmax is col 2
    router = np.zeros((16, 4), np.float32)
    router[:, 2] = 10.0
    bp["router"] = jnp.asarray(router)
    rs = np.random.RandomState(2)
    x = jnp.asarray(np.abs(rs.randn(1, 8, 16)) + 0.1, jnp.float32)
    y, _ = m._moe_mlp(bp, x)
    y = np.asarray(y).reshape(8, 16)
    # capacity = ceil(0.5 * 8 / 4) = 1: first token served, rest dropped to 0
    assert np.abs(y[0]).max() > 0
    np.testing.assert_array_equal(y[1:], np.zeros_like(y[1:]))


def test_moe_masked_tokens_claim_no_capacity():
    """Padding tokens (attention_mask 0) must not occupy expert slots: with a
    tight capacity, identical pad rows would otherwise flood one expert and
    evict real tokens that arrive later in flat order."""
    spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                               num_experts=4, moe_every=1, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=8, dropout=0.0, capacity_factor=1.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    bp = dict(params["block_0"])
    router = np.zeros((16, 4), np.float32)
    router[:, 1] = 10.0  # everything wants expert 1; capacity = 8*1/4 = 2
    bp["router"] = jnp.asarray(router)
    rs = np.random.RandomState(0)
    x = jnp.asarray(np.abs(rs.randn(1, 8, 16)) + 0.1, jnp.float32)
    # first 6 tokens are padding, last 2 are real
    mask = jnp.asarray([[0, 0, 0, 0, 0, 0, 1, 1]], jnp.float32)
    y, aux = m._moe_mlp(bp, x, token_mask=mask)
    y = np.asarray(y).reshape(8, 16)
    # pad tokens produce nothing and claim nothing; both real tokens fit
    np.testing.assert_array_equal(y[:6], np.zeros_like(y[:6]))
    assert np.abs(y[6]).max() > 0 and np.abs(y[7]).max() > 0
    # without the mask, the pad flood evicts the real tokens (sanity check
    # that the scenario is the one the mask is protecting against)
    y2, _ = m._moe_mlp(bp, x)
    y2 = np.asarray(y2).reshape(8, 16)
    assert np.abs(y2[6:]).max() == 0


def test_moe_flops_scale_with_tokens_not_experts():
    # capacity routing: expert FLOPs follow the token count, not E; the old
    # all-experts einsum made the E=8 model ~4x the E=2 model's FLOPs
    def flops(num_experts):
        spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                                   num_experts=num_experts, moe_every=1,
                                   hidden=64, num_layers=2, num_heads=2,
                                   mlp_dim=512, max_len=32, dropout=0.0)
        m = model_from_json(spec)
        params = m.init(jax.random.PRNGKey(0))
        ids = jnp.zeros((4, 32), jnp.int32)

        def loss(p):
            return m.loss_vector(p, {"input_ids": ids}, train=False).mean()

        ca = jax.jit(loss).lower(params).compile().cost_analysis()
        if isinstance(ca, list):  # pre-0.6 jax: one dict per computation
            ca = ca[0]
        return ca["flops"]

    assert flops(8) < 1.6 * flops(2)


def test_moe_aux_loss_encourages_balance():
    spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                               num_experts=4, hidden=16, num_layers=2,
                               num_heads=2, mlp_dim=32, max_len=8,
                               dropout=0.0, router_aux_weight=0.0)
    m0 = model_from_json(spec)
    params = m0.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 20, (4, 8)), jnp.int32)
    base = float(m0.loss_vector(params, {"input_ids": ids}, train=False).mean())
    spec1 = build_registry_spec("transformer_moe_lm", vocab_size=20,
                                num_experts=4, hidden=16, num_layers=2,
                                num_heads=2, mlp_dim=32, max_len=8,
                                dropout=0.0, router_aux_weight=0.5)
    m1 = model_from_json(spec1)
    with_aux = float(m1.loss_vector(params, {"input_ids": ids}, train=False).mean())
    assert with_aux > base  # aux term present (>= 1.0 * weight by construction)


def test_filter_pspec_drops_unknown_axes():
    mesh = make_mesh({"ep": 8})
    assert filter_pspec(P(None, "tp"), mesh) == P(None, None)
    assert filter_pspec(P("ep", None), mesh) == P("ep", None)
    assert mesh_axis_size(mesh, "ep") == 8
    assert mesh_axis_size(mesh, "tp") == 1


def test_distributed_helpers_single_process():
    from sparkflow_tpu.parallel import distributed as dist
    dist.initialize()  # no-op in single process
    mesh = dist.global_mesh({"dp": -1})
    assert mesh.devices.size == len(jax.devices())
    assert dist.process_local_batch(64) == 64
    assert ":" in dist.determine_master()


def test_moe_top2_routing_matches_per_token_mixture():
    """router_top_k=2 (GShard style): with ample capacity each token's output
    is the gate-weighted mixture of its two chosen experts' FFNs."""
    spec = build_registry_spec("transformer_moe_lm", vocab_size=20,
                               num_experts=4, moe_every=1, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=8, dropout=0.0, capacity_factor=4.0,
                               router_top_k=2)
    m = model_from_json(spec)
    bp = m.init(jax.random.PRNGKey(0))["block_0"]
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(2, 8, 16), jnp.float32)
    y, aux = m._moe_mlp(bp, x)
    xf = np.asarray(x).reshape(-1, 16)
    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(xf @ np.asarray(bp["router"])), axis=-1))
    expect = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        top2 = np.argsort(probs[t])[::-1][:2]
        g = probs[t, top2] / probs[t, top2].sum()
        for gi, ei in zip(g, top2):
            hmid = np.asarray(jax.nn.gelu(jnp.asarray(
                xf[t] @ np.asarray(bp["experts_fc1"])[ei]
                + np.asarray(bp["experts_b1"])[ei])))
            expect[t] += gi * (hmid @ np.asarray(bp["experts_fc2"])[ei]
                               + np.asarray(bp["experts_b2"])[ei])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), expect,
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0


def test_moe_top2_trains_and_shards():
    spec = build_registry_spec("transformer_moe_lm", vocab_size=30,
                               num_experts=8, moe_every=1, hidden=16,
                               num_layers=2, num_heads=2, mlp_dim=32,
                               max_len=8, dropout=0.0, router_top_k=2)
    m = model_from_json(spec)
    mesh = make_mesh({"ep": 8})
    params = shard_params(m.init(jax.random.PRNGKey(0)), mesh, m.param_pspecs())
    opt = build_optimizer("adam", 1e-2, None)
    state = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 30, (4, 8)), jnp.int32)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: m.loss_vector(
            p, {"input_ids": ids}, train=False).mean())(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    first = None
    for i in range(8):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < first


def test_moe_all_to_all_shardmap_matches_replicated():
    """The shard_map all_to_all EP path (GShard pipeline: route -> exchange
    -> local experts -> exchange back) must match the single-device
    capacity-dispatch model with the same weights, and train."""
    from sparkflow_tpu.parallel.ep import (make_moe_shardmap_train_step,
                                           place_moe_params)

    mesh = make_mesh({"ep": 8})
    kw = dict(vocab_size=40, num_experts=8, moe_every=1, hidden=32,
              num_layers=2, num_heads=4, mlp_dim=64, max_len=16,
              dropout=0.0, capacity_factor=8.0)
    m_a2a = model_from_json(build_registry_spec("transformer_moe_lm",
                                                ep_axis="ep", **kw))
    m_ref = model_from_json(build_registry_spec("transformer_moe_lm", **kw))
    params = m_ref.init(jax.random.PRNGKey(0))

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 40, (16, 16)), jnp.int32)
    mask = jnp.ones((16, 16), jnp.float32)

    opt = build_optimizer("gradient_descent", 0.05, None)
    placed = place_moe_params(m_a2a, jax.tree.map(jnp.copy, params), mesh)
    step = make_moe_shardmap_train_step(m_a2a, opt, mesh)
    state = opt.init(placed)
    placed, state, loss = step(placed, state, ids, mask, jax.random.PRNGKey(1))

    ref_loss = m_ref.loss_vector(
        params, {"input_ids": ids, "attention_mask": mask},
        train=False).mean()
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)

    first = float(loss)
    for i in range(5):
        placed, state, loss = step(placed, state, ids, mask,
                                   jax.random.PRNGKey(i + 2))
    assert float(loss) < first
    # expert shards stayed sharded through the update
    assert "ep" in str(placed["block_0"]["experts_fc1"].sharding.spec)


def test_moe_a2a_top2_matches_gspmd_top2():
    """The all_to_all dispatch at router_top_k=2 must match the GSPMD
    capacity-dispatch model with the same weights (capacity covers every
    choice, so neither form drops tokens)."""
    from sparkflow_tpu.parallel.ep import (make_moe_shardmap_train_step,
                                           place_moe_params)

    mesh = make_mesh({"ep": 8})
    kw = dict(vocab_size=40, num_experts=8, moe_every=1, hidden=32,
              num_layers=2, num_heads=4, mlp_dim=64, max_len=16,
              dropout=0.0, capacity_factor=8.0, router_top_k=2)
    m_a2a = model_from_json(build_registry_spec("transformer_moe_lm",
                                                ep_axis="ep", **kw))
    m_ref = model_from_json(build_registry_spec("transformer_moe_lm", **kw))
    params = m_ref.init(jax.random.PRNGKey(0))

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 40, (16, 16)), jnp.int32)
    mask = jnp.ones((16, 16), jnp.float32)

    opt = build_optimizer("gradient_descent", 0.05, None)
    placed = place_moe_params(m_a2a, jax.tree.map(jnp.copy, params), mesh)
    step = make_moe_shardmap_train_step(m_a2a, opt, mesh)
    p2, _, loss = step(placed, opt.init(placed), ids, mask,
                       jax.random.PRNGKey(1))

    ref_loss = m_ref.loss_vector(
        params, {"input_ids": ids, "attention_mask": mask},
        train=False).mean()
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    # the one-step update matches the replicated model's update too
    import optax
    g = jax.grad(lambda p: m_ref.loss_vector(
        p, {"input_ids": ids, "attention_mask": mask},
        train=False).mean())(params)
    sgd = optax.apply_updates(params, jax.tree.map(lambda x: -0.05 * x, g))
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(sgd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_moe_a2a_overflow_fraction_metric():
    """return_overflow reports the dropped fraction: generous capacity -> 0;
    a starved capacity_factor must drop a nonzero fraction of choices."""
    from functools import partial

    from sparkflow_tpu.jax_compat import shard_map
    from sparkflow_tpu.ops.moe_dispatch import all_to_all_moe_ffn

    mesh = make_mesh({"ep": 4}, devices=jax.devices()[:4])
    e, h, m = 4, 8, 16
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 4, h), jnp.float32)
    router = jnp.asarray(rs.randn(h, e), jnp.float32)
    fc1 = jnp.asarray(rs.randn(e, h, m) * 0.1, jnp.float32)
    b1 = jnp.zeros((e, m), jnp.float32)
    fc2 = jnp.asarray(rs.randn(e, m, h) * 0.1, jnp.float32)
    b2 = jnp.zeros((e, h), jnp.float32)

    def run(cf):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
                 out_specs=(P("ep"), P("ep"), P("ep")),
                 check_vma=False)
        def f(x, router, fc1, b1, fc2, b2):
            y, aux, ovf = all_to_all_moe_ffn(
                x, router, fc1, b1, fc2, b2, "ep", e, capacity_factor=cf,
                top_k=2, return_overflow=True)
            return y, aux[None], ovf[None]
        return f(x, router, fc1, b1, fc2, b2)

    _, _, ovf_generous = run(float(e))
    assert float(jnp.max(ovf_generous)) == 0.0
    _, _, ovf_tight = run(0.25)
    assert float(jnp.mean(ovf_tight)) > 0.05


def test_moe_a2a_outside_shardmap_fails_actionably():
    m = model_from_json(build_registry_spec(
        "transformer_moe_lm", vocab_size=20, num_experts=4, moe_every=1,
        ep_axis="ep", hidden=16, num_layers=1, num_heads=2, mlp_dim=32,
        max_len=8, dropout=0.0))
    p = m.init(jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 8), jnp.int32)
    with pytest.raises(NameError, match="make_moe_shardmap_train_step"):
        m.loss_vector(p, {"input_ids": ids}, train=False)


def test_pp_composes_with_dp(pp_setup):
    """pp(4) x dp(2): batch sharded over dp, stages over pp — one step must
    match the single-device loss/update (dropout 0, equal shards)."""
    m, params = pp_setup
    mesh = make_mesh({"dp": 2, "pp": 4})
    opt = build_optimizer("gradient_descent", 0.1, None)
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 40, (8, 16)), jnp.int32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)

    pp = shard_params(split_stage_params(m, params, 4), mesh,
                      pp_pspecs(split_stage_params(m, params, 4)))
    step = make_pp_train_step(m, opt, mesh, n_microbatches=2)
    p2, _, loss = step(pp, opt.init(pp), ids, y, jax.random.PRNGKey(5))
    ref = m.loss_vector(params, {"input_ids": ids, "y": y},
                        train=False).mean()
    np.testing.assert_allclose(float(loss), float(ref), atol=1e-4)

    # the update equals plain single-device SGD on the same global batch
    import optax
    def ref_loss(p):
        return m.loss_vector(p, {"input_ids": ids, "y": y},
                             train=False).mean()
    g = jax.grad(ref_loss)(params)
    sgd_params = optax.apply_updates(params, jax.tree.map(lambda x: -0.1 * x, g))
    back = merge_stage_params(m, p2)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(sgd_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_pp_lm_task_matches_single_device(sched):
    """Pipeline-parallel causal LM (task='lm'): loss and the SGD update must
    match the single-device transformer_lm on the same batch."""
    import optax
    spec = build_registry_spec("transformer_lm", vocab_size=40, hidden=32,
                               num_layers=8, num_heads=4, mlp_dim=64,
                               max_len=16, dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    pp = shard_params(split_stage_params(m, params, 4), mesh,
                      pp_pspecs(split_stage_params(m, params, 4)))
    opt = build_optimizer("gradient_descent", 0.1, None)
    step = make_pp_train_step(m, opt, mesh, n_microbatches=2, task="lm",
                              schedule=sched)
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 40, (8, 16)), jnp.int32)
    mask = jnp.ones((8, 16), jnp.float32)
    p2, _, loss = step(pp, opt.init(pp), ids, mask, jax.random.PRNGKey(9))

    def ref_loss(p):
        return m.loss_vector(p, {"input_ids": ids, "attention_mask": mask},
                             train=False).mean()

    np.testing.assert_allclose(float(loss), float(ref_loss(params)),
                               atol=1e-4)
    g = jax.grad(ref_loss)(params)
    sgd = optax.apply_updates(params, jax.tree.map(lambda x: -0.1 * x, g))
    back = merge_stage_params(m, p2)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(sgd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_hierarchical_psum_mean_matches_flat():
    """The DCN-aware two-level reduction (reduce_scatter over ICI -> psum
    the 1/n_ici shard over DCN -> all_gather) equals a flat psum-mean over
    both axes exactly — incl. leaves whose size does not divide the ICI
    axis (flat-pad path)."""
    from sparkflow_tpu.jax_compat import shard_map

    from sparkflow_tpu.parallel.collectives import hierarchical_psum_mean

    mesh = make_mesh({"dcn": 2, "dp": 4})
    rs = np.random.RandomState(0)
    # 7 and 10 don't divide dp=4; (3,5) exercises reshape; scalar-ish leaf too
    tree = {"a": jnp.asarray(rs.randn(7), jnp.float32),
            "b": jnp.asarray(rs.randn(3, 5), jnp.float32),
            "c": jnp.asarray(rs.randn(8), jnp.float32)}

    def per_device(seed_tree):
        # each device contributes a deterministic distinct tree
        i = jax.lax.axis_index("dcn") * 4 + jax.lax.axis_index("dp")
        contrib = jax.tree.map(lambda x: x * (1.0 + i), seed_tree)
        hier = hierarchical_psum_mean(contrib, ici_axis="dp", dcn_axis="dcn")
        flat = jax.tree.map(
            lambda x: jax.lax.psum(x, ("dcn", "dp")) / 8.0, contrib)
        return hier, flat

    hier, flat = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(),), out_specs=(P(), P()),
        check_vma=False))(tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(hier[k]), np.asarray(flat[k]),
                                   rtol=1e-6)


def test_dp_shardmap_two_level_matches_flat():
    """make_dp_shardmap_train_step(dcn_axis=...) on a {dcn,dp} mesh: one
    step's updated params equal the flat single-axis dp step's on the same
    batch — the hierarchical wire layout changes traffic, not math."""
    from sparkflow_tpu.parallel.dp import make_dp_shardmap_train_step

    spec = build_registry_spec("transformer_classifier", vocab_size=32,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=8,
                               dropout=0.0)
    m = model_from_json(spec)
    opt = build_optimizer("adam", 1e-3, None)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 32, (16, 8)), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)])
    mask = jnp.ones((16,), jnp.float32)
    p0 = m.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    mesh2 = make_mesh({"dcn": 2, "dp": 4})
    step2 = make_dp_shardmap_train_step(m, opt, mesh2, "input_ids", "y",
                                        dcn_axis="dcn")
    p_a = jax.tree.map(jnp.array, p0)
    p_a, _, loss_a = step2(p_a, opt.init(p_a), ids, y, mask, rng)

    # flat reference on a 1-axis mesh with the same total devices: dropout
    # is off and grads are exact means, so device-index rng folds don't
    # enter the update math
    mesh1 = make_mesh({"dp": 8})
    step1 = make_dp_shardmap_train_step(m, opt, mesh1, "input_ids", "y")
    p_b = jax.tree.map(jnp.array, p0)
    p_b, _, loss_b = step1(p_b, opt.init(p_b), ids, y, mask, rng)

    assert abs(float(loss_a) - float(loss_b)) < 1e-5
    for ka in p_a:
        for la, lb in zip(jax.tree.leaves(p_a[ka]), jax.tree.leaves(p_b[ka])):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       atol=5e-5)
