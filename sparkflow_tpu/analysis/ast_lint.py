"""AST rules for JAX footguns (GC-A2xx) over repo and user source.

Purely syntactic — scanned files are parsed, never imported, so linting
``examples/`` doesn't need pyspark and linting a broken module doesn't
crash the pass. The flip side is that detection is *best effort*: a
function is treated as traced when the tracing is visible in the same
module (a ``@jax.jit``-style decorator, or its name passed to
``jax.jit`` / ``jax.lax.scan`` / ``shard_map`` / ... in an enclosing
scope); functions returned from factories and jitted by a *different*
module are invisible to this pass — the jaxpr/runtime analyzers cover
those.

Rules
-----
GC-A201  host-sync-in-jit   ``.item()``/``.tolist()``/``.numpy()``/
                            ``.block_until_ready()``, ``print``, and
                            ``float()/int()/bool()/np.asarray()`` applied to
                            a traced argument, inside a traced function.
GC-A202  traced-branch      Python ``if``/``while`` testing a traced
                            argument (``is None`` structure checks exempt).
GC-A203  prng-key-reuse     the same key name consumed by two sampling
                            calls with no intervening rebind (branch-aware;
                            applies to every function, traced or not).
GC-A204  unhashable-static  a jit-static argument whose default is a
                            list/dict/set — unhashable at cache-key time.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, filter_suppressed

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_py_files"]


# decorator / callee names (last attribute component) that trace the
# function they're applied to, mapped to which argument positions are traced
_TRACING_DECORATORS = {"jit", "pmap", "vmap", "grad", "value_and_grad",
                       "checkpoint", "remat", "filter_jit"}
_TRACING_CALLS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pmap": (0,), "vmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "checkpoint": (0,), "remat": (0,),
    "make_jaxpr": (0,), "eval_shape": (0,), "named_call": (0,),
    "scan": (0,), "associative_scan": (0,), "map": (0,),  # lax.map only

    "while_loop": (0, 1), "fori_loop": (2,), "cond": (1, 2),
    "switch": (1, 2, 3, 4), "shard_map": (0,), "custom_vjp": (0,),
    "custom_jvp": (0,),
}

_HOST_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_HOST_SYNC_CASTS = {"float", "int", "bool"}
_HOST_SYNC_NP = {"asarray", "array", "ascontiguousarray", "copy", "save"}
# numpy-ish module aliases whose .asarray/.array pull data to the host
_NP_ALIASES = {"np", "numpy", "onp"}
# jax.random functions that do NOT consume their key argument
_PRNG_NONCONSUMING = {"split", "fold_in", "PRNGKey", "key", "clone",
                      "key_data", "wrap_key_data", "key_impl",
                      "default_prng_impl"}


def _last_attr(node: ast.AST) -> Optional[str]:
    """Final dotted component of a Name/Attribute chain, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _attr_chain(node: ast.AST) -> List[str]:
    """['jax', 'random', 'normal'] for jax.random.normal; [] if not a
    plain dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _is_prng_call(call: ast.Call) -> Optional[str]:
    """The jax.random function name if ``call`` looks like one, else None.
    Matches ``jax.random.X`` / ``jrandom.X`` / ``random.X`` chains — the
    penultimate component must mention 'random'."""
    chain = _attr_chain(call.func)
    if len(chain) >= 2 and "random" in chain[-2].lower():
        return chain[-1]
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _walk_shallow(fn: ast.AST):
    """Walk ``fn``'s body without descending into nested function defs —
    those are linted separately against their own parameter sets."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


class _FnInfo:
    __slots__ = ("node", "scope", "traced", "reason")

    def __init__(self, node, scope):
        self.node = node
        self.scope = scope  # enclosing Module/FunctionDef/ClassDef node
        self.traced = False
        self.reason = ""


class _Index(ast.NodeVisitor):
    """One pass over the module: function defs per scope + which local
    names are handed to tracing transforms in which scope."""

    def __init__(self, tree: ast.Module):
        self.fns: Dict[ast.AST, _FnInfo] = {}
        self._by_scope: Dict[int, Dict[str, ast.AST]] = {}
        self._assigned: Dict[int, Set[str]] = {}
        self._scope_stack: List[ast.AST] = [tree]
        self._register_block(tree, tree.body)
        self._collect_assigned(tree, tree)
        for stmt in tree.body:
            self.visit(stmt)

    def _register_block(self, scope: ast.AST, body: Sequence[ast.stmt]):
        table = self._by_scope.setdefault(id(scope), {})
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                table[stmt.name] = stmt
                self.fns[stmt] = _FnInfo(stmt, scope)

    def _collect_assigned(self, scope: ast.AST, root: ast.AST) -> None:
        """Names bound by plain assignment in this scope: they shadow any
        same-named def during resolution (the binding is opaque to us)."""
        assigned = self._assigned.setdefault(id(scope), set())
        for node in _walk_shallow(root):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets = [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                targets = [node.optional_vars]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        assigned.add(n.id)

    def _resolve(self, name: str) -> Optional[ast.AST]:
        for scope in reversed(self._scope_stack):
            if isinstance(scope, ast.ClassDef):
                continue  # class bodies aren't enclosing scopes in Python
            hit = self._by_scope.get(id(scope), {}).get(name)
            if hit is not None:
                return hit
            if name in self._assigned.get(id(scope), ()):
                return None  # shadowed by a non-def binding we can't follow
        return None

    def _mark(self, fn_node: Optional[ast.AST], reason: str):
        info = self.fns.get(fn_node)
        if info is not None and not info.traced:
            info.traced = True
            info.reason = reason

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope_stack.append(node)
        self._register_block(node, node.body)
        for stmt in node.body:
            self.visit(stmt)
        self._scope_stack.pop()

    def _visit_fn(self, node):
        # defs nested inside if/try/with blocks weren't seen by the
        # enclosing block's pre-pass — register them into the current scope
        if node not in self.fns:
            scope = self._scope_stack[-1]
            self.fns[node] = _FnInfo(node, scope)
            self._by_scope.setdefault(id(scope), {})[node.name] = node
        for dec in node.decorator_list:
            name = _last_attr(dec)
            if isinstance(dec, ast.Call):
                fname = _last_attr(dec.func)
                if fname in _TRACING_DECORATORS:
                    self._mark(node, f"@{fname}(...)")
                elif fname == "partial" and dec.args:
                    inner = _last_attr(dec.args[0])
                    if inner in _TRACING_DECORATORS:
                        self._mark(node, f"@partial({inner}, ...)")
            elif name in _TRACING_DECORATORS:
                self._mark(node, f"@{name}")
        self._scope_stack.append(node)
        self._register_block(node, node.body)
        self._collect_assigned(node, node)
        for stmt in node.body:
            self.visit(stmt)
        self._scope_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call):
        fname = _last_attr(node.func)
        positions = _TRACING_CALLS.get(fname or "")
        if fname == "map":
            # lax.map traces its callback; jax.tree.map / tree_map do not
            chain = _attr_chain(node.func)
            if len(chain) < 2 or chain[-2] != "lax":
                positions = None
        if positions:
            for pos in positions:
                if pos < len(node.args) and isinstance(node.args[pos],
                                                       ast.Name):
                    self._mark(self._resolve(node.args[pos].id),
                               f"passed to {fname}()")
        self.generic_visit(node)


def _propagate_nested(index: _Index) -> None:
    """A def nested in a traced function runs at trace time too."""
    changed = True
    while changed:
        changed = False
        for info in index.fns.values():
            if info.traced:
                continue
            parent = index.fns.get(info.scope)
            if parent is not None and parent.traced:
                info.traced = True
                info.reason = f"nested in traced {parent.node.name}()"
                changed = True


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


# ---------------------------------------------------------------------------
# GC-A201 / GC-A202: rules inside traced functions
# ---------------------------------------------------------------------------


def _traced_fn_findings(fn: ast.AST, params: Set[str], path: str
                        ) -> List[Finding]:
    out: List[Finding] = []
    fname = fn.name

    def mentions_param(expr: ast.AST) -> bool:
        return bool(_names_in(expr) & params)

    for node in _walk_shallow(fn):
        if isinstance(node, ast.Call):
            callee = node.func
            if isinstance(callee, ast.Attribute) \
                    and callee.attr in _HOST_SYNC_METHODS:
                out.append(Finding(
                    "GC-A201",
                    f".{callee.attr}() inside traced {fname}() forces a "
                    f"host sync (or fails on a tracer) — keep device "
                    f"values on device",
                    path=path, line=node.lineno, source="ast_lint"))
            elif isinstance(callee, ast.Name) and callee.id == "print":
                out.append(Finding(
                    "GC-A201",
                    f"print() inside traced {fname}() runs at trace time "
                    f"only (and prints tracers) — use jax.debug.print",
                    path=path, line=node.lineno, source="ast_lint"))
            elif isinstance(callee, ast.Name) \
                    and callee.id in _HOST_SYNC_CASTS and node.args \
                    and mentions_param(node.args[0]):
                out.append(Finding(
                    "GC-A201",
                    f"{callee.id}() on a traced value inside {fname}() "
                    f"synchronizes (ConcretizationTypeError under jit)",
                    path=path, line=node.lineno, source="ast_lint"))
            else:
                chain = _attr_chain(callee)
                if (len(chain) >= 2 and chain[0] in _NP_ALIASES
                        and chain[-1] in _HOST_SYNC_NP and node.args
                        and mentions_param(node.args[0])):
                    out.append(Finding(
                        "GC-A201",
                        f"{'.'.join(chain)}() on a traced value inside "
                        f"{fname}() pulls it to the host — use jnp",
                        path=path, line=node.lineno, source="ast_lint"))
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            # occurrences that are static under jit: isinstance()/hasattr()/
            # callable()/len() arguments, and .shape/.ndim/.size/.dtype
            # attribute reads — shapes and python types are trace constants
            static_ids: Set[int] = set()
            for sub in ast.walk(test):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id in ("isinstance", "hasattr",
                                            "callable", "len")):
                    for arg in sub.args:
                        static_ids.update(id(n) for n in ast.walk(arg))
                elif (isinstance(sub, ast.Attribute)
                        and sub.attr in ("shape", "ndim", "size", "dtype")):
                    static_ids.update(id(n) for n in ast.walk(sub.value))
            hits = {n.id for n in ast.walk(test)
                    if isinstance(n, ast.Name) and n.id in params
                    and id(n) not in static_ids}
            # `x is None` / `x is not None` checks pytree STRUCTURE, which
            # is static under jit — exempt names used only that way
            for cmp in ast.walk(test):
                if (isinstance(cmp, ast.Compare)
                        and len(cmp.ops) == 1
                        and isinstance(cmp.ops[0], (ast.Is, ast.IsNot))
                        and isinstance(cmp.left, ast.Name)):
                    hits.discard(cmp.left.id)
            if hits:
                kw = "while" if isinstance(node, ast.While) else "if"
                out.append(Finding(
                    "GC-A202",
                    f"`{kw}` on traced argument(s) {sorted(hits)} of "
                    f"{fname}() — data-dependent Python control flow; use "
                    f"jnp.where / lax.cond / lax.while_loop",
                    path=path, line=node.lineno, source="ast_lint"))
    return out


# ---------------------------------------------------------------------------
# GC-A203: PRNG key reuse (branch-aware straight-line scan, every function)
# ---------------------------------------------------------------------------


def _prng_findings(fn: ast.AST, path: str) -> List[Finding]:
    findings: Dict[Tuple[int, str], Finding] = {}

    def consume_in_expr(expr: ast.AST, consumed: Dict[str, int]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            prng_fn = _is_prng_call(node)
            if prng_fn is None or prng_fn in _PRNG_NONCONSUMING:
                continue
            if node.args and isinstance(node.args[0], ast.Name):
                key = node.args[0].id
                if key in consumed:
                    findings.setdefault((node.lineno, key), Finding(
                        "GC-A203",
                        f"PRNG key {key!r} already consumed by jax.random."
                        f"* at line {consumed[key]} is sampled again in "
                        f"{fn.name}() — split it (identical keys give "
                        f"identical 'randomness')",
                        path=path, line=node.lineno, source="ast_lint"))
                else:
                    consumed[key] = node.lineno

    def clear_targets(target: ast.AST, consumed: Dict[str, int]) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                consumed.pop(node.id, None)

    def scan(stmts: Sequence[ast.stmt], consumed: Dict[str, int]
             ) -> Dict[str, int]:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # own scope, scanned separately
            if isinstance(st, ast.Assign):
                consume_in_expr(st.value, consumed)
                for t in st.targets:
                    clear_targets(t, consumed)
            elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                if st.value is not None:
                    consume_in_expr(st.value, consumed)
                clear_targets(st.target, consumed)
            elif isinstance(st, ast.If):
                consume_in_expr(st.test, consumed)
                left = scan(st.body, dict(consumed))
                right = scan(st.orelse, dict(consumed))
                consumed = dict(consumed)
                # a branch that can't fall through (trailing return/raise/
                # break/continue) never reaches the code after the if — its
                # consumed keys must not leak into the fallthrough path
                def falls_through(stmts):
                    return not (stmts and isinstance(
                        stmts[-1], (ast.Return, ast.Raise, ast.Break,
                                    ast.Continue)))
                branches = [b for b, body in ((left, st.body),
                                              (right, st.orelse))
                            if falls_through(body)]
                for branch in branches:
                    for k, v in branch.items():
                        consumed[k] = min(v, consumed.get(k, v))
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(st, ast.While):
                    consume_in_expr(st.test, consumed)
                else:
                    consume_in_expr(st.iter, consumed)
                    clear_targets(st.target, consumed)
                # two passes catch loop-carried reuse; rebinds inside the
                # body clear state so rotated keys stay clean
                consumed = scan(st.body, consumed)
                consumed = scan(st.body, consumed)
                consumed = scan(st.orelse, consumed)
            elif isinstance(st, ast.With):
                for item in st.items:
                    consume_in_expr(item.context_expr, consumed)
                consumed = scan(st.body, consumed)
            elif isinstance(st, ast.Try):
                consumed = scan(st.body, dict(consumed))
                for h in st.handlers:
                    consumed.update(scan(h.body, dict(consumed)))
                consumed = scan(st.orelse, consumed)
                consumed = scan(st.finalbody, consumed)
            elif isinstance(st, (ast.Return, ast.Expr)) \
                    and st.value is not None:
                consume_in_expr(st.value, consumed)
            elif isinstance(st, (ast.Raise, ast.Assert)):
                for sub in ast.iter_child_nodes(st):
                    consume_in_expr(sub, consumed)
        return consumed

    scan(fn.body, {})
    return list(findings.values())


# ---------------------------------------------------------------------------
# GC-A204: unhashable static-arg defaults
# ---------------------------------------------------------------------------


def _static_spec_from_call(call: ast.Call):
    nums, names = None, None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = kw.value
        elif kw.arg == "static_argnames":
            names = kw.value
    return nums, names


def _literal_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return out
    return []


def _literal_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _unhashable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _last_attr(node.func) in ("list", "dict", "set", "bytearray")
    return False


def _static_default_findings(fn: ast.AST, call: ast.Call, path: str
                             ) -> List[Finding]:
    nums_node, names_node = _static_spec_from_call(call)
    if nums_node is None and names_node is None:
        return []
    a = fn.args
    pos = a.posonlyargs + a.args
    static_params: List[ast.arg] = []
    for i in _literal_ints(nums_node) if nums_node is not None else []:
        if 0 <= i < len(pos):
            static_params.append(pos[i])
    wanted = set(_literal_strs(names_node) if names_node is not None else [])
    for p in pos + a.kwonlyargs:
        if p.arg in wanted:
            static_params.append(p)
    # align defaults: the last len(defaults) positional args have them
    defaults = dict(zip([p.arg for p in pos[len(pos) - len(a.defaults):]],
                        a.defaults))
    defaults.update({p.arg: d for p, d in zip(a.kwonlyargs, a.kw_defaults)
                     if d is not None})
    out = []
    for p in static_params:
        d = defaults.get(p.arg)
        if d is not None and _unhashable_default(d):
            out.append(Finding(
                "GC-A204",
                f"argument {p.arg!r} of {fn.name}() is jit-static but "
                f"defaults to an unhashable {type(d).__name__.lower()} — "
                f"jit's cache key will raise TypeError; use a tuple or "
                f"frozen container",
                path=path, line=fn.lineno, source="ast_lint"))
    return out


def _unhashable_static_findings(tree: ast.Module, index: _Index, path: str
                                ) -> List[Finding]:
    out: List[Finding] = []
    for info in index.fns.values():
        for dec in info.node.decorator_list:
            if isinstance(dec, ast.Call):
                fname = _last_attr(dec.func)
                if fname in ("jit", "filter_jit") or (
                        fname == "partial" and dec.args
                        and _last_attr(dec.args[0]) in ("jit", "filter_jit")):
                    out.extend(_static_default_findings(info.node, dec, path))
    by_name = {info.node.name: info.node for info in index.fns.values()}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and _last_attr(node.func) == "jit"
                and node.args and isinstance(node.args[0], ast.Name)):
            fn = by_name.get(node.args[0].id)
            if fn is not None:
                out.extend(_static_default_findings(fn, node, path))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        # a file the interpreter can't parse is its own problem; report
        # nothing rather than crash the sweep over every other file
        return []
    index = _Index(tree)
    _propagate_nested(index)
    findings: List[Finding] = []
    for info in index.fns.values():
        if info.traced:
            findings.extend(_traced_fn_findings(info.node,
                                                _param_names(info.node),
                                                path))
        findings.extend(_prng_findings(info.node, path))
    findings.extend(_unhashable_static_findings(tree, index, path))
    findings.sort(key=lambda f: (f.line or 0, f.rule))
    return filter_suppressed(findings, source)


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f))
    return findings
