"""FLOPs/MFU accounting (utils.flops) — the bench ladder's roofline math."""

import jax
import jax.numpy as jnp
import numpy as np

from sparkflow_tpu.utils.flops import (attention_flops, device_peak_flops,
                                       jit_flops, mfu,
                                       transformer_train_step_flops,
                                       train_step_flops)


def test_jit_flops_counts_matmul():
    a = jnp.zeros((64, 128), jnp.float32)
    b = jnp.zeros((128, 32), jnp.float32)
    fl = jit_flops(lambda x, y: x @ y, a, b)
    # 2*m*k*n MACs-as-flops; XLA may count fused epilogue ops too
    assert fl is not None
    assert 0.9 * (2 * 64 * 128 * 32) <= fl <= 1.5 * (2 * 64 * 128 * 32)


def test_transformer_flops_formula():
    # BERT-base seq-512 batch-16: the canonical ~4.6e12 flops/step
    # (2*tokens*matmul-params fwd, bwd=2x, + attention matmuls)
    fl = transformer_train_step_flops(16, 512, 768, 12, 3072, num_classes=2)
    assert 4.0e12 < fl < 5.5e12
    # causal halves only the attention term
    causal = transformer_train_step_flops(16, 512, 768, 12, 3072,
                                          num_classes=2, causal=True)
    assert causal < fl
    diff = fl - causal
    attn_half = 0.5 * 3 * 4 * 16 * 512 * 512 * 768 * 12
    np.testing.assert_allclose(diff, attn_half, rtol=1e-6)


def test_attention_flops():
    fwd = attention_flops(2, 8, 4096, 4096, 64)
    assert fwd == 4.0 * 2 * 8 * 4096 * 4096 * 64
    assert attention_flops(2, 8, 4096, 4096, 64, causal=True) == fwd / 2
    assert attention_flops(2, 8, 4096, 4096, 64, with_backward=True) == 3 * fwd


def test_mfu_off_tpu_is_none():
    if jax.devices()[0].platform != "tpu":
        assert device_peak_flops() is None
        assert mfu(1e12) is None
    assert mfu(None, 197e12) is None
    assert mfu(98.5e12, 197e12) == 0.5


def test_train_step_flops_on_graph_model():
    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.graphdef import GraphModel
    from sparkflow_tpu.optimizers import build_optimizer

    def model():
        x = nn.placeholder([None, 32], name="x")
        y = nn.placeholder([None, 4], name="y")
        out = nn.dense(nn.dense(x, 64, activation="relu"), 4, name="out")
        nn.softmax_cross_entropy(y, out)

    m = GraphModel.from_json(build_graph(model))
    opt = build_optimizer("adam", 1e-3, None)
    rs = np.random.RandomState(0)
    x = rs.rand(128, 32).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 128)]
    fl = train_step_flops(m, "x:0", "y:0", opt, x, y)
    assert fl is not None
    # fwd+bwd matmuls dominate; XLA drops the dead input-layer dx matmul,
    # so the floor is fwd + (2x fwd - dx1) ~ 2.1x forward matmul flops
    fwd_mm = 2 * 128 * (32 * 64 + 64 * 4)
    assert fl >= 2.0 * fwd_mm
