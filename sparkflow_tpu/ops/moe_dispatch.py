"""all_to_all expert dispatch: the communicating form of expert parallelism.

``models/moe.py``'s capacity dispatch runs under GSPMD (the expert einsum's
sharding makes XLA insert the collective). This module is the explicit
shard_map form — the GShard pipeline (Lepikhin et al.; PAPERS.md pattern):

    route locally -> all_to_all token buffers over the ``ep`` axis ->
    each device runs ONLY its local experts -> all_to_all back -> combine

Every device holds a batch shard AND ``E/n`` experts of the bank; tokens
move to their expert's device over ICI and return. With ``E == n`` (one
expert per device — the common pod configuration) there is zero redundant
FLOP anywhere. Used inside ``shard_map`` (see
``parallel/ep.make_moe_shardmap_train_step``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def all_to_all_moe_ffn(x, router_w, experts_fc1, experts_b1, experts_fc2,
                       experts_b2, axis_name: str, num_experts: int,
                       capacity_factor: float = 1.25, token_mask=None):
    """Top-1 routed expert FFN with all_to_all dispatch.

    Args (device-local views inside shard_map over ``axis_name``):
      x            [B_local, S, H] token activations (batch sharded)
      router_w     [H, E] replicated router
      experts_fc1  [E_local, H, M] — THIS device's slice of the expert bank
      experts_b1   [E_local, M]
      experts_fc2  [E_local, M, H]
      experts_b2   [E_local, H]
      token_mask   optional [B_local, S]; masked tokens claim no capacity

    Returns ``(combined [B_local, S, H], aux_loss scalar-per-device)``.
    The aux loss is the Switch load-balance term over LOCAL tokens; callers
    typically ``pmean`` it across the axis.
    """
    try:
        n = jax.lax.axis_size(axis_name)
    except NameError as e:
        raise NameError(
            f"mesh axis {axis_name!r} is not bound: an ep_axis MoE model "
            f"must run inside shard_map over that axis — use "
            f"parallel.ep.make_moe_shardmap_train_step (or build the model "
            f"without ep_axis for the GSPMD dispatch)") from e
    b, s, h = x.shape
    nl = b * s                      # local tokens
    e = num_experts
    e_local = experts_fc1.shape[0]
    assert e_local * n == e, (e_local, n, e)
    # per (device -> peer) buffer capacity: tokens THIS device may send to
    # one peer. cf * nl / n is the balanced share; generous by design.
    cap = max(1, int(-(-capacity_factor * nl // n)))

    xf = x.reshape(nl, h)
    logits = jnp.einsum("th,he->te", xf.astype(jnp.float32), router_w)
    probs = jax.nn.softmax(logits, axis=-1)                 # [Nl, E]
    expert_idx = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    gate = jnp.max(probs, axis=-1)
    live = (token_mask.reshape(nl).astype(jnp.float32)
            if token_mask is not None else jnp.ones((nl,), jnp.float32))

    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32) * live[:, None]
    aux = e * jnp.sum((jnp.sum(onehot, axis=0)
                       / jnp.maximum(jnp.sum(live), 1.0))
                      * (jnp.sum(probs * live[:, None], axis=0)
                         / jnp.maximum(jnp.sum(live), 1.0)))

    # destination peer for each token + position in that peer's send buffer
    dest = expert_idx // e_local                            # [Nl]
    dest_oh = jax.nn.one_hot(dest, n, dtype=jnp.float32) * live[:, None]
    pos = jnp.sum((jnp.cumsum(dest_oh, axis=0) - 1.0) * dest_oh,
                  axis=-1).astype(jnp.int32)
    kept = (pos < cap) & (live > 0)
    slot = jnp.where(kept, dest * cap + pos, n * cap)       # overflow bin

    # scatter tokens into [n, cap] send buffers (+1 overflow row)
    token_for_slot = jnp.full((n * cap + 1,), nl, dtype=jnp.int32)
    token_for_slot = token_for_slot.at[slot].set(
        jnp.arange(nl, dtype=jnp.int32))[:n * cap]
    xf_pad = jnp.concatenate([xf, jnp.zeros((1, h), xf.dtype)], axis=0)
    send_x = xf_pad[token_for_slot].reshape(n, cap, h)
    # sidecar: which LOCAL expert on the destination + validity
    le_pad = jnp.concatenate(
        [(expert_idx % e_local), jnp.zeros((1,), jnp.int32)])
    send_le = le_pad[token_for_slot].reshape(n, cap)
    send_valid = (token_for_slot < nl).astype(jnp.float32).reshape(n, cap)

    # the exchange: slab j of send goes to peer j; recv slab j came from j
    recv_x = jax.lax.all_to_all(send_x, axis_name, 0, 0, tiled=False)
    recv_le = jax.lax.all_to_all(send_le, axis_name, 0, 0, tiled=False)
    recv_valid = jax.lax.all_to_all(send_valid, axis_name, 0, 0, tiled=False)

    # local expert compute over the n*cap received tokens; one-hot combine
    # over E_local only (E_local == 1 on E == n meshes: no redundancy)
    rt = recv_x.reshape(n * cap, h)
    le_oh = (jax.nn.one_hot(recv_le.reshape(-1), e_local, dtype=jnp.float32)
             * recv_valid.reshape(-1)[:, None])             # [n*cap, E_local]
    hid = jnp.einsum("th,ehm->etm", rt, experts_fc1.astype(rt.dtype))
    hid = jax.nn.gelu(hid + experts_b1.astype(hid.dtype)[:, None, :])
    out = jnp.einsum("etm,emh->eth", hid, experts_fc2.astype(hid.dtype))
    out = out + experts_b2.astype(out.dtype)[:, None, :]
    out = jnp.einsum("eth,te->th", out, le_oh.astype(out.dtype))

    # send results home and combine into original token positions
    back = jax.lax.all_to_all(out.reshape(n, cap, h), axis_name, 0, 0,
                              tiled=False)
    back_pad = jnp.concatenate([back.reshape(n * cap, h),
                                jnp.zeros((1, h), back.dtype)], axis=0)
    y = back_pad[slot] * gate[:, None].astype(back.dtype)
    return y.reshape(b, s, h).astype(x.dtype), aux
