"""Native dataplane (queue + CSV), streaming fit, metrics, tracing."""

import os
import threading

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.trainer import Trainer
from sparkflow_tpu.utils.data import BatchQueue, load_csv_matrix
from sparkflow_tpu.utils.metrics import Metrics, timer
from sparkflow_tpu.native.build import load_library


def test_native_library_builds():
    # the image ships g++; if this fails the numpy fallback still works but we
    # want to know the native path regressed
    assert load_library() is not None


def test_csv_loader_roundtrip(tmp_path):
    rs = np.random.RandomState(0)
    m = rs.rand(50, 7).astype(np.float32)
    p = str(tmp_path / "m.csv")
    np.savetxt(p, m, delimiter=",", fmt="%.6f")
    a = load_csv_matrix(p)
    assert a.shape == (50, 7)
    np.testing.assert_allclose(a, m, atol=1e-5)


def test_batch_queue_preserves_rows_and_masks():
    rs = np.random.RandomState(1)
    M = rs.rand(250, 5).astype(np.float32)
    Y = rs.rand(250, 2).astype(np.float32)
    q = BatchQueue(batch_size=64, row_dim=5, label_dim=2, capacity=3,
                   shuffle=True, seed=7)

    def produce():
        for i in range(0, 250, 90):
            q.push(M[i:i + 90], Y[i:i + 90])
        q.finish()

    threading.Thread(target=produce, daemon=True).start()
    rows, total = [], 0
    for x, y, mask, n in q:
        assert x.shape == (64, 5) and mask.sum() == n
        assert np.all(x[n:] == 0)  # padding is zeroed
        rows.append(x[:n])
        total += n
    q.close()
    assert total == 250
    got = np.concatenate(rows)
    np.testing.assert_allclose(np.sort(got[:, 0]), np.sort(M[:, 0]), atol=1e-6)


def test_batch_queue_unsupervised():
    q = BatchQueue(batch_size=16, row_dim=3, label_dim=0, capacity=2,
                   shuffle=False)
    q.push(np.ones((10, 3), np.float32))
    q.finish()
    x, y, mask, n = q.pop()
    assert n == 10 and y.shape[1] == 0
    assert q.pop() is None
    q.close()


def test_fit_stream_learns():
    rs = np.random.RandomState(0)
    M = rs.randn(600, 12).astype(np.float32)
    lbl = (M @ rs.randn(12) > 0).astype(np.float32)

    def m():
        x = nn.placeholder([None, 12], name="x")
        y = nn.placeholder([None, 1], name="y")
        nn.sigmoid_cross_entropy(y, nn.dense(x, 1, name="out"))

    tr = Trainer(build_graph(m), "x:0", "y:0", mini_batch_size=64,
                 learning_rate=0.2)
    res = tr.fit_stream(zip(list(M), list(lbl)))
    assert res.losses[-1] < res.losses[0]
    assert len(res.losses) == -(-600 // 64)


def test_metrics_registry():
    m = Metrics()
    for i in range(5):
        m.scalar("loss", 1.0 / (i + 1), step=i)
    m.incr("steps", 5)
    with timer("fake", m):
        pass
    s = m.summary()
    assert s["loss"]["count"] == 5 and s["loss"]["last"] == 0.2
    assert s["counters"]["steps"] == 5
    assert "time/fake" in s


def test_metrics_jsonl_dump(tmp_path):
    m = Metrics()
    m.scalar("a", 1.0)
    p = str(tmp_path / "m.jsonl")
    m.dump_jsonl(p)
    import json
    lines = [json.loads(l) for l in open(p)]
    assert lines[0]["name"] == "a"


def test_metrics_histogram_percentiles():
    m = Metrics()
    for v in range(1, 101):  # 1..100: pN is ~N at 1% granularity
        m.observe("latency_ms", float(v))
    assert m.percentile("latency_ms", 0) == 1.0
    assert m.percentile("latency_ms", 100) == 100.0
    assert m.percentile("latency_ms", 50) == pytest.approx(50.5)
    ps = m.percentiles("latency_ms")
    assert set(ps) == {"p50", "p95", "p99"}
    assert ps["p95"] == pytest.approx(95.05)
    assert ps["p99"] == pytest.approx(99.01)
    assert ps["p50"] <= ps["p95"] <= ps["p99"]
    h = m.histograms()["latency_ms"]
    assert h["count"] == 100 and h["min"] == 1.0 and h["max"] == 100.0
    assert h["mean"] == pytest.approx(50.5)
    with pytest.raises(KeyError):
        m.percentile("nope", 50)
    from sparkflow_tpu.utils.metrics import _Histogram
    with pytest.raises(ValueError):
        _Histogram().percentile(50)  # empty histogram


def test_metrics_histogram_reservoir_bounded():
    from sparkflow_tpu.utils.metrics import HISTOGRAM_RESERVOIR
    m = Metrics()
    n = HISTOGRAM_RESERVOIR * 3
    for v in range(n):
        m.observe("big", float(v))
    h = m._hists["big"]
    assert len(h.samples) == HISTOGRAM_RESERVOIR  # memory stays bounded
    s = m.histograms()["big"]
    assert s["count"] == n  # exact stats survive the sampling
    assert s["min"] == 0.0 and s["max"] == float(n - 1)
    # reservoir-sampled median of a uniform ramp lands near the true median
    assert abs(m.percentile("big", 50) - (n - 1) / 2) < n * 0.05


def test_metrics_histogram_concurrent_observe():
    m = Metrics()

    def worker(k):
        for v in range(200):
            m.observe("shared", float(v + k))

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m._hists["shared"].count == 8 * 200


def test_metrics_histogram_in_summary_and_jsonl(tmp_path):
    m = Metrics()
    assert "histograms" not in m.summary()  # only present once observed
    m.observe("h", 2.0)
    m.observe("h", 4.0)
    s = m.summary()
    assert s["histograms"]["h"]["count"] == 2
    p = str(tmp_path / "m.jsonl")
    m.dump_jsonl(p)
    import json
    hist_lines = [json.loads(l) for l in open(p) if "histogram" in l]
    assert hist_lines and hist_lines[0]["name"] == "h"
    assert hist_lines[0]["histogram"]["mean"] == pytest.approx(3.0)
    m.reset()
    assert m.histograms() == {}


def test_tracing_annotate_runs():
    import jax
    import jax.numpy as jnp
    from sparkflow_tpu.utils.tracing import annotate

    with annotate("test-region"):
        v = jax.jit(lambda x: x * 2)(jnp.ones(4))
    assert float(v.sum()) == 8.0


def test_reference_import_paths():
    """Every module path a reference user imports exists here with the same
    public symbols (swap `sparkflow` -> `sparkflow_tpu` and code ports):
    tensorflow_async, tensorflow_model_loader, HogwildSparkModel, RWLock,
    ml_util, graph_utils, pipeline_util (reference tree listing)."""
    from sparkflow_tpu.tensorflow_async import SparkAsyncDL, SparkAsyncDLModel
    from sparkflow_tpu.tensorflow_model_loader import (
        attach_tensorflow_model_to_pipeline, load_tensorflow_model)
    from sparkflow_tpu.HogwildSparkModel import HogwildSparkModel
    from sparkflow_tpu.RWLock import RWLock
    from sparkflow_tpu.ml_util import (convert_json_to_weights,
                                       convert_weights_to_json, predict_func)
    from sparkflow_tpu.graph_utils import build_adam_config, build_graph
    from sparkflow_tpu.pipeline_util import (PysparkPipelineWrapper,
                                             PysparkReaderWriter)
    for sym in (SparkAsyncDL, SparkAsyncDLModel, load_tensorflow_model,
                attach_tensorflow_model_to_pipeline, HogwildSparkModel,
                RWLock, predict_func, convert_weights_to_json,
                convert_json_to_weights, build_graph, build_adam_config,
                PysparkPipelineWrapper, PysparkReaderWriter):
        assert callable(sym) or isinstance(sym, type)
