"""examples/ must RUN, not just compile.

The reference treats its examples as Docker smoke tests
(``/root/reference/Makefile:4-11``, ``.travis.yml:15-19``); mirroring that,
every example executes end-to-end here — ``main`` path, fit, transform,
save/load — as a subprocess on the virtual CPU mesh in SPARKFLOW_TPU_SMOKE
mode (tiny iters/rows; the knob each example honors). A broken example turns
CI red instead of shipping green behind a string grep.

Structural pins stay too: the repo-root sys.path bootstrap (directly
runnable from any cwd) and the wedged-relay guard (no hang on a dead
accelerator tunnel).
"""

import os
import py_compile
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _example_files():
    return sorted(f for f in os.listdir(EXAMPLES) if f.endswith(".py"))


@pytest.mark.parametrize("fname", _example_files())
def test_example_compiles(fname):
    py_compile.compile(os.path.join(EXAMPLES, fname), doraise=True)


@pytest.mark.parametrize("fname", _example_files())
def test_example_has_path_bootstrap(fname):
    src = open(os.path.join(EXAMPLES, fname)).read()
    assert "sys.path.insert" in src, (
        f"{fname} lacks the repo-root sys.path bootstrap; "
        f"`python examples/{fname}` would fail with ModuleNotFoundError")


@pytest.mark.parametrize("fname", _example_files())
def test_example_guards_against_wedged_relay(fname):
    src = open(os.path.join(EXAMPLES, fname)).read()
    assert "ensure_live_backend" in src, (
        f"{fname} never calls ensure_live_backend(); it would hang forever "
        f"on a wedged TPU relay instead of falling back to CPU")


@pytest.mark.slow  # full end-to-end subprocess train per example: minutes of
# wall clock across the matrix — out of the tier-1 budget, run with `-m slow`
@pytest.mark.parametrize("fname", _example_files())
def test_example_executes(fname, tmp_path):
    """Run the example's real ``__main__`` path to completion (smoke mode,
    CPU mesh, cwd=tmp so save artifacts don't litter the repo)."""
    env = dict(os.environ)
    env.update({
        "SPARKFLOW_TPU_SMOKE": "1",
        "JAX_PLATFORMS": "cpu",  # honored in-process by ensure_live_backend
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    env.pop("PYTHONPATH", None)  # examples bootstrap their own sys.path
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, fname)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, (
        f"{fname} failed (rc={proc.returncode}):\n--- stdout ---\n"
        f"{proc.stdout[-3000:]}\n--- stderr ---\n{proc.stderr[-3000:]}")
