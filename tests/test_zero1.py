"""ZeRO-1 weight-update sharding: numerics parity, checkpoint interop, and
the estimator Param surface.

The parity bar: the zero1 step (reduce_scatter -> shard-local update ->
all_gather, optimizers_sharded.sharded_update) must match the replicated dp
step per-optimizer within PINNED tolerances — the two paths differ only in
float reduction order. Models use a ragged hidden width so no param count
divides the 8-way dp axis (exercising the flatten/pad path).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkflow_tpu.models.presets import mlp
from sparkflow_tpu.optimizers import AVAILABLE_OPTIMIZERS, build_optimizer
from sparkflow_tpu.optimizers_sharded import (gather_zero1_state,
                                              has_per_param_state,
                                              place_zero1_state,
                                              shard_zero1_state,
                                              sharded_update,
                                              state_bytes_per_device)
from sparkflow_tpu.parallel.dp import (make_dp_shardmap_train_step,
                                       make_dp_zero1_train_step)
from sparkflow_tpu.parallel.mesh import make_mesh
from sparkflow_tpu.trainer import Trainer

# reduction-order float drift only: both paths compute the same math
ATOL = 5e-5
RTOL = 1e-5

pytestmark = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs the 8-virtual-device harness")


def _model():
    from sparkflow_tpu.models import model_from_json
    # hidden=17 -> every weight/bias size is ragged mod 8
    return model_from_json(mlp(10, 3, hidden=(17,)))


def _data(n=64):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(n, 10), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)])
    mask = jnp.ones((n,), jnp.float32)
    return x, y, mask


def _max_diff(a, b):
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


@pytest.mark.parametrize("opt_name", AVAILABLE_OPTIMIZERS)
def test_zero1_matches_replicated_all_optimizers(opt_name):
    """Two steps of zero1 vs the replicated dp step, every registry
    optimizer, ragged param sizes, dp=8."""
    m = _model()
    opt = build_optimizer(opt_name, 1e-2, None)
    mesh = make_mesh({"dp": 8})
    x, y, mask = _data()
    p0 = m.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    stepR = make_dp_shardmap_train_step(m, opt, mesh, "x:0", "y:0")
    pR = jax.tree.map(jnp.array, p0)
    sR = opt.init(pR)

    stepZ = make_dp_zero1_train_step(m, opt, mesh, "x:0", "y:0")
    pZ = jax.tree.map(jnp.array, p0)
    sZ = place_zero1_state(sharded_update(opt, 8, "dp").init(pZ), mesh, 8)

    for i in range(2):
        r = jax.random.fold_in(rng, i)
        pR, sR, lR = stepR(pR, sR, x, y, mask, r)
        pZ, sZ, lZ = stepZ(pZ, sZ, x, y, mask, r)
        assert abs(float(lR) - float(lZ)) < ATOL
    for a, b in zip(jax.tree.leaves(pR), jax.tree.leaves(pZ)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=ATOL, rtol=RTOL)
    # the sharded states agree too, compared in the standard layout
    # (pad lanes are don't-care and excluded by the gather)
    stdZ = gather_zero1_state(opt, pZ, sZ, 8)
    for a, b in zip(jax.tree.leaves(sR), jax.tree.leaves(stdZ)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=ATOL, rtol=RTOL)


def test_zero1_state_bytes_shrink_per_device():
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    mesh = make_mesh({"dp": 8})
    params = m.init(jax.random.PRNGKey(0))
    repl = jax.device_put(opt.init(params),
                          jax.sharding.NamedSharding(
                              mesh, jax.sharding.PartitionSpec()))
    z = place_zero1_state(sharded_update(opt, 8, "dp").init(params), mesh, 8)
    full = state_bytes_per_device(repl)
    shard = state_bytes_per_device(z)
    # mu+nu shard 8-way; only the scalar count replicates
    assert shard < full / 6


def test_gather_shard_roundtrip_across_dp_sizes():
    """Standard -> zero1(dp=8) -> standard -> zero1(dp=4): the standard form
    is invariant, so checkpoints move between mesh shapes."""
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    params = m.init(jax.random.PRNGKey(0))
    std = opt.init(params)
    # make leaves non-trivial so the reshape/trim paths are actually checked
    std = jax.tree.map(
        lambda l: l + jnp.arange(l.size, dtype=l.dtype).reshape(l.shape)
        if getattr(l, "ndim", 0) >= 1 else l, std)
    z8 = shard_zero1_state(opt, params, std, 8)
    back = gather_zero1_state(opt, params, z8, 8)
    for a, b in zip(jax.tree.leaves(std), jax.tree.leaves(back)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    z4 = shard_zero1_state(opt, params, back, 4)
    back4 = gather_zero1_state(opt, params, z4, 4)
    for a, b in zip(jax.tree.leaves(std), jax.tree.leaves(back4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_trainer_zero1_matches_replicated_fit():
    rs = np.random.RandomState(0)
    X = rs.randn(96, 10).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 96)]
    mesh = make_mesh({"dp": 8})
    g = mlp(10, 3, hidden=(17,))

    def fit(wus):
        t = Trainer(g, "x:0", "y:0", optimizer="adam", learning_rate=0.01,
                    iters=3, mini_batch_size=32, mesh=mesh, seed=0,
                    weight_update_sharding=wus)
        return t, t.fit(X, Y)

    t_off, r_off = fit("off")
    t_on, r_on = fit("on")
    assert not t_off._zero1_active and t_on._zero1_active
    np.testing.assert_allclose(r_off.losses, r_on.losses, atol=ATOL)
    assert _max_diff(r_off.params, r_on.params) < ATOL


def test_trainer_zero1_checkpoint_roundtrip(tmp_path):
    """zero1 fits checkpoint the STANDARD opt state: a zero1 run resumes
    bit-exactly, and the directory restores into a zero1-OFF trainer."""
    rs = np.random.RandomState(1)
    X = rs.randn(64, 10).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    mesh = make_mesh({"dp": 8})
    g = mlp(10, 3, hidden=(17,))

    def fit(wus, d):
        t = Trainer(g, "x:0", "y:0", optimizer="adam", learning_rate=0.01,
                    iters=3, mini_batch_size=32, mesh=mesh, seed=0,
                    weight_update_sharding=wus, checkpoint_dir=str(d),
                    checkpoint_every=1)
        return t.fit(X, Y)

    d = tmp_path / "ck"
    r1 = fit("on", d)
    r2 = fit("on", d)     # resumes at the final epoch; params unchanged
    assert _max_diff(r1.params, r2.params) == 0.0
    r3 = fit("off", d)    # replicated trainer reads the same directory
    assert _max_diff(r1.params, r3.params) == 0.0


def test_zero1_auto_gating():
    rs = np.random.RandomState(2)
    X = rs.randn(64, 10).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    mesh = make_mesh({"dp": 8})
    g = mlp(10, 3, hidden=(17,))

    def fit(**kw):
        t = Trainer(g, "x:0", "y:0", iters=1, mini_batch_size=32, mesh=mesh,
                    **kw)
        t.fit(X, Y)
        return t._zero1_active

    assert fit(optimizer="adam")                       # per-param state, dp=8
    assert not fit(optimizer="gradient_descent")       # stateless: no win
    assert not fit(optimizer="adam",
                   optimizer_options={"clip_norm": 1.0})  # global-norm clip
    # meshless fit never activates
    t = Trainer(g, "x:0", "y:0", optimizer="adam", iters=1,
                mini_batch_size=32, mesh=None)
    t.fit(X, Y)
    assert not t._zero1_active
    # 'on' where ineligible warns and falls back instead of dying
    t = Trainer(g, "x:0", "y:0", optimizer="adam", iters=1,
                mini_batch_size=32, mesh=None, weight_update_sharding="on")
    t.fit(X, Y)
    assert not t._zero1_active
    with pytest.raises(ValueError, match="weight_update_sharding"):
        Trainer(g, "x:0", "y:0", weight_update_sharding="sideways")


def test_has_per_param_state():
    m = _model()
    params = m.init(jax.random.PRNGKey(0))
    assert has_per_param_state(build_optimizer("adam", 1e-2, None), params)
    assert not has_per_param_state(
        build_optimizer("gradient_descent", 1e-2, None), params)


def test_dp_less_mesh_trains_cleanly():
    """ADVICE #1: a mesh without a 'dp' axis (e.g. pure-pp) used to die at
    core's NamedSharding(mesh, P('dp')) with an opaque unknown-axis error;
    the epoch jit now degrades those rows to replicated."""
    from sparkflow_tpu.models import build_registry_spec, model_from_json
    spec = build_registry_spec("transformer_classifier", vocab_size=32,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=8,
                               dropout=0.0)
    m = model_from_json(spec)
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 32, (16, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    t = Trainer(m, "input_ids", "y", optimizer="adam", iters=2,
                mini_batch_size=8, mesh=mesh, seed=0)
    r = t.fit(ids, y)
    assert len(r.losses) == 2 and np.isfinite(r.losses).all()


def test_dcn_axis_equal_dp_raises_actionable():
    """ADVICE #3: dcn_axis == dp_axis fails fast with a message naming both
    axes, not deep inside psum with a duplicate-axis error."""
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    mesh = make_mesh({"dp": 8})
    with pytest.raises(ValueError, match="DIFFERENT mesh axis"):
        make_dp_shardmap_train_step(m, opt, mesh, "x:0", "y:0",
                                    dcn_axis="dp")
    with pytest.raises(ValueError, match="DIFFERENT mesh axis"):
        make_dp_zero1_train_step(m, opt, mesh, "x:0", "y:0", dcn_axis="dp")
    with pytest.raises(ValueError, match="not a mesh axis"):
        make_dp_shardmap_train_step(m, opt, mesh, "x:0", "y:0",
                                    dcn_axis="nope")


def test_zero1_two_level_dcn_matches_flat():
    """zero1 with the hierarchical ICI/DCN reduction on a {dcn,dp} mesh
    matches the flat single-axis zero1 step (and hence the replicated one)."""
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    x, y, mask = _data(32)
    p0 = m.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    mesh2 = make_mesh({"dcn": 2, "dp": 4})
    step2 = make_dp_zero1_train_step(m, opt, mesh2, "x:0", "y:0",
                                     dcn_axis="dcn")
    pA = jax.tree.map(jnp.array, p0)
    sA = place_zero1_state(sharded_update(opt, 4, "dp", "dcn").init(pA),
                           mesh2, 4)
    pA, sA, lA = step2(pA, sA, x, y, mask, rng)

    mesh1 = make_mesh({"dp": 8})
    step1 = make_dp_zero1_train_step(m, opt, mesh1, "x:0", "y:0")
    pB = jax.tree.map(jnp.array, p0)
    sB = place_zero1_state(sharded_update(opt, 8, "dp").init(pB), mesh1, 8)
    pB, sB, lB = step1(pB, sB, x, y, mask, rng)

    assert abs(float(lA) - float(lB)) < ATOL
    for a, b in zip(jax.tree.leaves(pA), jax.tree.leaves(pB)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=ATOL)


def test_estimator_weight_update_sharding_param():
    """Param plumbing: default 'auto', round-trips through setParams, and a
    bad value fails validation before any training."""
    from sparkflow_tpu.spark_async import SparkAsyncDL
    est = SparkAsyncDL(inputCol="features", tensorflowGraph=mlp(10, 3),
                       tfInput="x:0", tfLabel="y:0", tfOutput="out:0",
                       labelCol="labels")
    assert est.getOrDefault(est.weightUpdateSharding) == "auto"
    est.setParams(weightUpdateSharding="off")
    assert est.getOrDefault(est.weightUpdateSharding) == "off"
    est.setParams(weightUpdateSharding="banana")
    with pytest.raises(ValueError, match="weightUpdateSharding"):
        est._validate_params()


def test_estimator_zero_stage_param():
    """zeroStage plumbing: default -1 (unset) leaves sharding=None so the
    legacy weightUpdateSharding knob stays in charge; a set stage maps
    through as_sharding_config into an explicit ShardingConfig request; an
    out-of-range stage fails validation before any training."""
    from sparkflow_tpu.spark_async import SparkAsyncDL
    est = SparkAsyncDL(inputCol="features", tensorflowGraph=mlp(10, 3),
                       tfInput="x:0", tfLabel="y:0", tfOutput="out:0",
                       labelCol="labels")
    assert est.getOrDefault(est.zeroStage) == -1
    assert est._sharding_config() is None
    est.setParams(zeroStage=2)
    est._validate_params()
    cfg = est._sharding_config()
    assert cfg is not None and cfg.zero_stage == 2
    est.setParams(zeroStage=3)
    assert est._sharding_config().zero_stage == 3
    est.setParams(zeroStage=7)
    with pytest.raises(ValueError, match="zeroStage"):
        est._validate_params()
