# Developer entry points (role parity with the reference's Makefile:1-17,
# which ran the examples and tests in Docker).

.PHONY: test test-fast test-pyspark docker-test-pyspark bench bench-ladder mfu-sweep baseline examples native clean serve-smoke sim-smoke fleet-smoke chaos-smoke lint-graft lint-graft-strict obs-smoke span-overhead elastic-smoke decode-smoke spec-smoke tp-smoke pp-smoke zero-smoke race-smoke swap-smoke kvquant-smoke scale-smoke trace-smoke

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -x -q -k "not estimator"

# real-pyspark e2e: installs pyspark (JVM required) and runs the mirrored
# reference suite on local[2], incl. the StopWordsRemover persistence carrier
test-pyspark:
	pip install "pyspark>=3.4"
	python -m pytest tests/test_pyspark_e2e.py -v

bench:
	python bench.py

bench-quick:
	python bench.py --quick

bench-ladder:
	python benchmarks/run_all.py

mfu-sweep:
	python benchmarks/mfu_sweep.py

baseline:
	python bench_baseline.py

# PYTHONPATH must APPEND the repo root: replacing it would clobber the axon
# TPU plugin's site dir (see .claude/skills/verify/SKILL.md gotchas)
examples:
	cd examples && PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python simple_dnn.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python cnn_example.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python autoencoder_example.py

docker-test-pyspark:
	docker compose run --rm --build test-pyspark

native:
	python -c "from sparkflow_tpu.native.build import load_library; \
	           print('native lib:', load_library(verbose=True))"

clean:
	rm -rf sparkflow_tpu/native/_build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +

# end-to-end serving smoke: start an InferenceServer on an ephemeral port,
# send one request through ServingClient, assert the prediction shape, stop
serve-smoke:
	PYTHONPATH=".:$$PYTHONPATH" python -c "\
	import numpy as np; \
	import sparkflow_tpu.nn as nn; \
	from sparkflow_tpu.graph_utils import build_graph; \
	from sparkflow_tpu.serving import InferenceEngine, InferenceServer, ServingClient; \
	g = lambda: (lambda x: nn.dense(nn.dense(x, 8, activation='relu'), 2, name='out'))(nn.placeholder([None, 4], name='x')); \
	rs = np.random.RandomState(0); \
	w = [rs.randn(4, 8).astype(np.float32), rs.randn(8).astype(np.float32), rs.randn(8, 2).astype(np.float32), rs.randn(2).astype(np.float32)]; \
	eng = InferenceEngine(build_graph(g), w, input_name='x:0', output_name='out/BiasAdd:0', max_batch=8); \
	srv = InferenceServer(eng, max_delay_ms=1.0).start(); \
	c = ServingClient(srv.url); \
	assert c.healthz()['status'] == 'ok'; \
	p = c.predict(rs.randn(3, 4).tolist()); \
	assert p.shape == (3, 2), p.shape; \
	srv.stop(); \
	print('serve-smoke OK: 3x2 prediction served at', srv.url)"

# fleet chaos smoke: the router test suite, then 3 real replica processes
# behind a RouterServer with a SIGKILL + same-port restart mid-burst —
# zero client-visible failures required (docs/serving.md)
fleet-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_router.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/fleet_smoke.py

# decode smoke: the decode test suite, then a real server subprocess
# serving a mixed-length /v1/generate burst — X-Request-Id echoed on every
# response, zero steady-state retraces, clean SIGTERM drain (docs/serving.md)
decode-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/decode_smoke.py

# speculative-decode smoke: the decode test suite, then a real server
# subprocess with speculation on — a mixed-length greedy burst must be
# token-identical to spec-off decode, zero steady-state retraces, clean
# SIGTERM drain; finishes with the spec-on/off benchmark (docs/serving.md)
spec-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/spec_smoke.py
	JAX_PLATFORMS=cpu python bench.py --spec-decode

# tensor-parallel serving smoke: the decode test suite, then a real server
# subprocess hosting a tp=2 mesh-sharded engine (spec decode + prefix cache
# on) — a concurrent mixed-length greedy burst must be token-identical to a
# tp=1 engine, zero steady-state retraces, clean SIGTERM drain; finishes
# with the tp=1 vs tp=2 decode benchmark (docs/serving.md)
tp-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/tp_smoke.py
	JAX_PLATFORMS=cpu python bench.py --tp-decode

# subprocess hosting a pp=2 stage-sharded engine (staged spec decode +
# prefix cache + chunked prefill on) — a concurrent mixed-length greedy
# burst must be token-identical to a pp=1 engine on both staged schedules
# (single-wave and micro-token wave), zero steady-state retraces, clean
# SIGTERM drain; finishes with the wave-vs-single-wave decode benchmark
# (docs/serving.md)
pp-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/pp_smoke.py
	JAX_PLATFORMS=cpu python bench.py --pp-decode

# chaos suite: deterministic fault injection against checkpoints, resume,
# coordinator joins, and serving drain (docs/resilience.md)
chaos-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q

# elastic bounded-staleness DP chaos suite (virtual-time stragglers,
# preemption, lease expiry) plus the sync-vs-elastic straggler benchmark
elastic-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q
	JAX_PLATFORMS=cpu python bench.py --elastic-straggler

# ZeRO stage sweep: the sharding test suite, then a stage 0->3 parity +
# checkpoint-interchange sweep and the two zero benches (docs/sharding.md)
zero-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_zero_sharding.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/zero_smoke.py
	JAX_PLATFORMS=cpu python bench.py --dp-zero2
	JAX_PLATFORMS=cpu python bench.py --dp-zero3

# graftcheck: sharding / tracing / concurrency lint over the repo's own
# source + the jaxpr self-check over presets x optimizers (docs/analysis.md)
lint-graft:
	JAX_PLATFORMS=cpu python -m sparkflow_tpu.analysis sparkflow_tpu examples

# the CI gate flavor: the same full pass (all GC families, including the
# GC-X6xx resource-lifecycle rules), exits nonzero on ANY finding — this
# is what tests/test_lint_gate.py pins as a tier-1 test
lint-graft-strict:
	JAX_PLATFORMS=cpu python -m sparkflow_tpu.analysis sparkflow_tpu examples --format json
	@echo "lint-graft-strict: clean"

# dynamic race smoke: the decode drain-under-load chaos scenario run
# entirely under the Eraser lockset detector (GC-R402) — zero empty-lockset
# reports required across engine/KV/metrics shared state (docs/analysis.md)
race-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/race_smoke.py

# live weight-publication smoke: the weightstore suite (crash-consistent
# publish, hot swap, canary gate, lock/race lints), then a real server
# subprocess hot-swapping weights mid-burst — one good publish (healthz
# version flips exactly once) and one corrupted publish (invisible to
# clients, last-good kept) with zero failures and a clean SIGTERM drain;
# finishes with the hot-swap inter-token latency benchmark (docs/serving.md)
swap-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_weightstore.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/swap_smoke.py
	JAX_PLATFORMS=cpu python bench.py --hot-swap

# quantized-KV smoke: the int8/fp8 pool battery (kernel dequant parity,
# running-scale appends, churn neutrality, composition parity), a
# real-server int8 smoke (16 concurrent mixed-length greedy generations
# with spec k=3 + prefix cache + chunked prefill, token-identical to
# full-precision decode, healthz advertising the pool layout, clean
# SIGTERM drain), then the capacity/parity/overload benchmark
# (docs/serving.md)
kvquant-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_kvquant.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/kvquant_smoke.py
	JAX_PLATFORMS=cpu python bench.py --kv-quant

# elastic autoscaling smoke: the autoscaler test battery (policy units,
# sim step response, live control loop, real-subprocess supervisor), then
# a real 1->3->1 fleet: load step up spawns replicas (zero-compile boot
# from the shared executable store), a SIGKILL mid-burst is reaped and
# replaced within one tick, the trickle phase drains back to min — zero
# client-visible failures throughout; finishes with the cold-start
# boot-to-first-token benchmark (docs/serving.md)
scale-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_autoscaler.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/scale_smoke.py
	JAX_PLATFORMS=cpu python bench.py --cold-start

# fleet-simulator smoke: the sim + policy-parity test suites, then the
# 1000-replica x 1M-request what-if with its capacity report, then the
# sim bench (scale wall-clock pin + legacy-vs-debit pick rule A/B)
sim-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_sim.py tests/test_policies.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/sim_smoke.py
	JAX_PLATFORMS=cpu python bench.py --sim

# observability smoke: the spans/stepstats/prometheus/request-tracing suite,
# then the span-overhead micro-bench (docs/observability.md)
obs-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q
	JAX_PLATFORMS=cpu python bench.py --span-overhead

span-overhead:
	JAX_PLATFORMS=cpu python bench.py --span-overhead

# distributed-tracing smoke: the tracing test battery (traceparent context,
# cross-process assembly, tail sampling, flight recorder + harvest), then a
# real 2-replica fleet: one hedged /v1/generate assembled into a single
# cross-process waterfall with the hedge loser labeled, and a SIGKILL
# postmortem naming the in-flight trace ids; finishes with the
# tracing-overhead benchmark (>= 0.98x tracing-off, docs/observability.md)
trace-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q
	JAX_PLATFORMS=cpu PYTHONPATH=".:$$PYTHONPATH" python examples/trace_smoke.py
	JAX_PLATFORMS=cpu python bench.py --trace-overhead

# round-2 example additions (text pipeline; TF1 migration needs tensorflow)
examples-extra:
	cd examples && PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python text_classifier.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python bert_classifier.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python tf1_migration.py && \
	PYTHONPATH="..:$$PYTHONPATH" SPARKFLOW_TPU_SMOKE=1 python rnn_sequence.py
