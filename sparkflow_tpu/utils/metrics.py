"""Structured training metrics (replaces the reference's print-based logging,
``sparkflow/HogwildSparkModel.py:94-98`` — SURVEY.md §5 "observability").

A process-local registry of counters/gauges/timings/histograms with JSONL
export and an optional per-step callback fan-out. Cheap enough to leave on:
recording is a dict update; device syncs only happen where the caller already
has a value. Histograms (``observe``/``percentile``) back the serving-side
latency metrics (p50/p95/p99) and are bounded by a reservoir cap so a
long-lived server never grows without limit.

Four value kinds, four write paths:

- ``scalar(name, v, step)`` — a time series (loss curves); every point kept.
- ``incr(name)``            — a monotone counter (requests served).
- ``gauge(name, v)``        — last-value-wins (queue depth, memory in use);
                              no history, one float per name.
- ``observe(name, v)``      — a distribution (latencies); reservoir-sampled.

Serving handlers record from many threads, so every read-modify-write —
including ``scalar``'s default-step computation and the listener snapshot —
happens under one registry lock. Listeners themselves are invoked *outside*
the lock (a listener that records back into the registry must not deadlock).
Prometheus text exposition of the whole registry lives in
:mod:`sparkflow_tpu.obs.exporters`.
"""

from __future__ import annotations

import json
import random
import re
import threading
import time
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Per-histogram sample cap. Beyond it, reservoir sampling keeps a uniform
# sample of the whole stream (percentiles stay unbiased) instead of the
# unbounded append a months-long serving process would otherwise pay for.
HISTOGRAM_RESERVOIR = 4096

# How many of the MOST RECENT observations each histogram also retains,
# in insertion order, for windowed percentiles. The reservoir above is a
# uniform sample of the whole stream — slicing its tail has no recency
# bias at all (overwrites land at random positions), so a "windowed"
# read off it would ossify: a past overload burst stays in the signal
# forever and a new one barely registers once the stream is long. The
# deque is the true sliding window; window= reads larger than this cap
# are clamped to it.
HISTOGRAM_WINDOW = 1024


class _Histogram:
    """Reservoir-sampled value distribution with exact count/min/max/sum
    plus a bounded insertion-ordered tail for windowed percentiles."""

    __slots__ = ("samples", "recent", "count", "total", "vmin", "vmax",
                 "_rng")

    def __init__(self, seed: int = 0):
        self.samples: List[float] = []
        self.recent: deque = deque(maxlen=HISTOGRAM_WINDOW)
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)
        self.recent.append(value)
        if len(self.samples) < HISTOGRAM_RESERVOIR:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < HISTOGRAM_RESERVOIR:
                self.samples[j] = value

    def percentile(self, q: float, window: Optional[int] = None) -> float:
        """Linear-interpolated q-th percentile (q in [0, 100]).

        Without ``window``: over the whole-stream reservoir sample. With
        ``window``: over exactly the last ``window`` observations (clamped
        to ``HISTOGRAM_WINDOW``) from the insertion-ordered tail — a true
        sliding window, so the autoscaler's p95 tracks what the fleet did
        in the last N requests, not a uniform sample of its whole life."""
        if not self.samples:
            raise ValueError("empty histogram")
        if window is None or int(window) <= 0:
            s = sorted(self.samples)
        else:
            s = sorted(list(self.recent)[-int(window):])
            if not s:
                # the sliding tail can be empty while the reservoir is not
                # (e.g. a histogram restored without its recent deque);
                # fall back to the whole-stream sample rather than index
                # into an empty list
                s = sorted(self.samples)
        if len(s) == 1:
            return s[0]
        pos = (q / 100.0) * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def summary(self) -> Dict[str, float]:
        if not self.samples:
            # an empty histogram summarizes to zeros (not ±inf min/max, not
            # a ValueError): snapshot/exposition paths must render whatever
            # exists without crashing on a series that never observed
            return {"count": self.count, "sum": self.total, "mean": 0.0,
                    "min": 0.0, "max": 0.0, "p50": 0.0, "p95": 0.0,
                    "p99": 0.0}
        return {"count": self.count, "sum": self.total,
                "mean": self.total / self.count if self.count else 0.0,
                "min": self.vmin, "max": self.vmax,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class Metrics:
    def __init__(self):
        self._scalars: Dict[str, List[tuple]] = defaultdict(list)
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, Tuple[float, float]] = {}  # name -> (v, ts)
        self._hists: Dict[str, _Histogram] = {}
        self._listeners: List[Callable[[str, float, int], None]] = []
        self._lock = threading.Lock()

    def scalar(self, name: str, value: float, step: Optional[int] = None) -> None:
        value = float(value)
        with self._lock:
            # the default step is "next index in this series" — a
            # read-modify-write that must not race with another recorder
            if step is None:
                step = len(self._scalars[name])
            self._scalars[name].append((step, value, time.time()))
            listeners = tuple(self._listeners)
        # fan out outside the lock: a listener recording back into this
        # registry (e.g. mirroring losses into a gauge) must not deadlock
        for fn in listeners:
            fn(name, value, step)

    def incr(self, name: str, amount: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += amount

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins instantaneous reading (queue depth, bytes in
        use). Unlike ``scalar`` it keeps no history — the natural shape for
        sampled state, and what Prometheus expects of a gauge."""
        with self._lock:
            self._gauges[name] = (float(value), time.time())

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the ``name`` histogram (latencies,
        batch sizes, fill ratios — anything whose distribution matters more
        than its last value)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(seed=len(self._hists))
            h.add(float(value))

    def percentile(self, name: str, q: float,
                   window: Optional[int] = None) -> float:
        """q-th percentile (q in [0, 100]) of histogram ``name``;
        ``window`` = only the most recent samples (see
        :meth:`_Histogram.percentile`)."""
        with self._lock:
            if name not in self._hists:
                raise KeyError(f"no histogram named {name!r}")
            return self._hists[name].percentile(q, window)

    def percentiles(self, name: str,
                    qs: Sequence[float] = (50, 95, 99)) -> Dict[str, float]:
        """{'p50': ..., 'p95': ..., 'p99': ...} for histogram ``name``."""
        return {f"p{g:g}": self.percentile(name, g) for g in qs}

    def subscribe(self, fn: Callable[[str, float, int], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def series(self, name: str) -> List[tuple]:
        with self._lock:
            return list(self._scalars.get(name, []))

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {name: v for name, (v, _) in self._gauges.items()}

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: h.summary() for name, h in self._hists.items()
                    if h.count}

    def _snapshot(self):
        """One consistent view of every table (single lock acquisition, so
        summary/JSONL export can't interleave with concurrent recorders)."""
        with self._lock:
            scalars = {name: list(pts) for name, pts in self._scalars.items()}
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: h.summary() for name, h in self._hists.items()
                     if h.count}
        return scalars, counters, gauges, hists

    def summary(self) -> Dict[str, Any]:
        scalars, counters, gauges, hists = self._snapshot()
        out: Dict[str, Any] = {"counters": counters}
        for name, pts in scalars.items():
            vals = [v for _, v, _ in pts]
            out[name] = {"last": vals[-1], "min": min(vals), "max": max(vals),
                         "count": len(vals)}
        if gauges:
            out["gauges"] = {name: v for name, (v, _) in gauges.items()}
        if hists:
            out["histograms"] = hists
        return out

    def dump_jsonl(self, path: str) -> None:
        scalars, counters, gauges, hists = self._snapshot()
        with open(path, "w") as f:
            for name, pts in scalars.items():
                for step, value, ts in pts:
                    f.write(json.dumps({"name": name, "step": step,
                                        "value": value, "ts": ts}) + "\n")
            for name, value in counters.items():
                f.write(json.dumps({"name": name, "counter": value}) + "\n")
            for name, (value, ts) in gauges.items():
                f.write(json.dumps({"name": name, "gauge": value,
                                    "ts": ts}) + "\n")
            for name, hist in hists.items():
                f.write(json.dumps({"name": name, "histogram": hist}) + "\n")

    def remove_prefix(self, prefix: str) -> int:
        """Drop every series whose name starts with ``prefix`` (all four
        tables); returns how many were removed. This is the deregistration
        path: a replica leaving the fleet must take its
        ``router/replica<i>/*`` gauges with it, or the exposition keeps
        advertising a ghost replica forever."""
        removed = 0
        with self._lock:
            for table in (self._scalars, self._counters, self._gauges,
                          self._hists):
                for name in [n for n in table if n.startswith(prefix)]:
                    del table[name]
                    removed += 1
        return removed

    def remove_matching(self, match) -> int:
        """Drop every series whose name matches ``match`` — a regex string
        (``re.search`` semantics) or a ``name -> bool`` callable — across
        all four tables; returns how many were removed. The general form of
        :meth:`remove_prefix` for cleanups a prefix can't express (e.g.
        one metric family across every replica: ``r"^router/replica\\d+/"
        "kv_pages_free$"``)."""
        if callable(match):
            pred = match
        else:
            pred = re.compile(match).search
        removed = 0
        with self._lock:
            for table in (self._scalars, self._counters, self._gauges,
                          self._hists):
                for name in [n for n in table if pred(n)]:
                    del table[name]
                    removed += 1
        return removed

    def reset(self) -> None:
        with self._lock:
            self._scalars.clear()
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


default_metrics = Metrics()


class timer:
    """``with timer('stage'):`` records wall seconds into the registry."""

    def __init__(self, name: str, metrics: Optional[Metrics] = None):
        self.name = name
        self.metrics = metrics or default_metrics

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.scalar(f"time/{self.name}", time.perf_counter() - self._t0)
        return False
