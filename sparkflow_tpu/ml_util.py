"""Weight serialization, inference kernel, and data marshalling.

Mirrors ``sparkflow/ml_util.py`` function-for-function, re-based on JAX:

- weights travel as a JSON list of nested lists in graph-node order — the same
  wire format as the reference (``sparkflow/ml_util.py:31-40``), with the flat
  order defined by :func:`sparkflow_tpu.graphdef.params_to_list` standing in for
  ``tf.trainable_variables`` order;
- :func:`predict_func` is the per-partition inference kernel
  (``sparkflow/ml_util.py:54-83``): rebuilds the model from JSON, runs the named
  output tensor, appends the prediction column (float for scalar outputs,
  ``Vectors.dense`` for vector outputs). Unlike the reference it runs in fixed
  -size chunks rather than one partition-sized batch (OOM anti-feature,
  SURVEY.md §"anti-features");
- the set-weights path has no analog of the reference's graph-growing
  ``tensorflow_set_weights`` hazard (``ml_util.py:16-28``): params are just a
  pytree value.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .compat import Row, Vectors
from .core import make_predict_fn, predict_in_chunks
from .graphdef import GraphModel, list_to_params, params_to_list
from .localml.linalg import vector_to_array


def get_weights(model: GraphModel, params) -> List[np.ndarray]:
    """Params pytree -> flat weight list (``tensorflow_get_weights`` analog)."""
    return params_to_list(model, params)


def set_weights(model: GraphModel, weights: List[np.ndarray]):
    """Flat weight list -> params pytree (``tensorflow_set_weights`` analog —
    but pure: returns a new pytree instead of mutating a session graph)."""
    return list_to_params(model, weights)


def convert_weights_to_json(weights: List[np.ndarray]) -> str:
    return json.dumps([np.asarray(w).tolist() for w in weights])


def convert_json_to_weights(json_weights: str) -> List[np.ndarray]:
    return [np.asarray(x, dtype=np.float32) for x in json.loads(json_weights)]


def resolve_weights(weights_str: str) -> List[np.ndarray]:
    """Decode a model's weight Param: inline JSON (reference wire format) or a
    side-file reference ``npz:<path>`` — the large-model escape hatch for the
    whole-weights-inside-pipeline-metadata anti-feature (SURVEY.md
    §anti-features; ``sparkflow/tensorflow_async.py:310``)."""
    if weights_str.startswith("npz:"):
        path = weights_str[4:]
        with np.load(path) as z:
            return [z[k] for k in sorted(z.files, key=lambda s: int(s.split("_")[-1]))]
    return convert_json_to_weights(weights_str)


def params_to_json(model: GraphModel, params) -> str:
    return convert_weights_to_json(params_to_list(model, params))


def json_to_params(model: GraphModel, json_weights: str):
    return list_to_params(model, convert_json_to_weights(json_weights))


# ---------------------------------------------------------------------------
# Inference kernel
# ---------------------------------------------------------------------------

# Keyed on the sha256 of the full graph JSON (not the 64-bit string hash — a
# collision there would silently serve the wrong model) and LRU-bounded so
# long-lived processes serving many models don't leak compiled programs.
_PREDICT_CACHE: "OrderedDict[Tuple[str, str, str, Optional[str], float], Any]" = OrderedDict()
_PREDICT_CACHE_MAX = 32


def _cached_predict_fn(graph_json: str, tf_output: str, tf_input,
                       tf_dropout: Optional[str], dropout_value: float,
                       quantize: Optional[str] = None,
                       mesh_axes: Optional[Dict[str, int]] = None):
    """Cache (model, predict_fn) across partitions — the reference rebuilt the
    whole session per partition (``ml_util.py:61-68``); one compiled program
    serves all partitions here. ``quantize`` ('weight_only'/'dynamic') keys
    separately (different params signature), as does ``mesh_axes`` (a
    mesh-sharded program: batch over 'dp', attention per shard)."""
    digest = hashlib.sha256(graph_json.encode()).hexdigest()
    in_key = (tuple(tf_input) if isinstance(tf_input, (list, tuple))
              else tf_input)
    mesh_key = tuple(sorted(mesh_axes.items())) if mesh_axes else None
    key = (digest, tf_output, in_key, tf_dropout, dropout_value, quantize,
           mesh_key)
    if key not in _PREDICT_CACHE:
        from .models import model_from_json
        model = model_from_json(graph_json)
        if quantize:
            model.quant_mode = quantize
        mesh = None
        if mesh_axes:
            from .parallel.mesh import make_mesh
            mesh = make_mesh(dict(mesh_axes))
        fn = make_predict_fn(model, tf_input, tf_output, tf_dropout,
                             dropout_value, mesh=mesh)
        _PREDICT_CACHE[key] = (model, fn)
        while len(_PREDICT_CACHE) > _PREDICT_CACHE_MAX:
            _PREDICT_CACHE.popitem(last=False)
    else:
        _PREDICT_CACHE.move_to_end(key)
    return _PREDICT_CACHE[key]


# quantized weight trees, keyed on the weights identity: quantizing the
# full tree per partition would undo the very amortization _PREDICT_CACHE
# exists for (the reference rebuilt its session per partition)
_QUANT_CACHE: "OrderedDict[str, Any]" = OrderedDict()
_QUANT_CACHE_MAX = 8


def _cached_quantized_params(model, graph_weights: str, quantize: str):
    from .graphdef import GraphModel
    from .utils.quant import MODES, quantize_params

    if quantize not in MODES:
        # validate HERE too: spark_async checks driver-side, but predict_func
        # is a documented serving API of its own — a typo'd mode must not
        # silently serve a different path
        raise ValueError(f"quantize must be one of {MODES}, got {quantize!r}")
    supports = (isinstance(model, GraphModel)
                or getattr(model, "SUPPORTS_INT8_SERVING", False))
    if not supports:
        raise ValueError(
            f"int8 serving (inferenceQuantize) supports graphdef models (the "
            f"nn DSL / build_graph), TF1 metagraphs, and the transformer "
            f"family; got {type(model).__name__} — serve this model without "
            f"quantization")
    # the tree is mode-agnostic (quant.py) but its scope/leaf naming is the
    # MODEL's, so the key pairs the model's param-tree naming with the
    # weights identity — the same flat weights served through two model
    # types (graphdef vs TF1 export of the same network) must not collide.
    # Derived IN here (not caller-supplied) so every entry point is covered.
    # npz side-files key on (path, mtime, size): the string digest would
    # serve stale weights after a refit overwrites the same path
    naming = hashlib.sha256(repr(
        [(scope, sorted(leaves)) for scope, leaves in
         model.param_specs().items()]).encode()).hexdigest()[:16]
    if graph_weights.startswith("npz:"):
        st = os.stat(graph_weights[4:])
        key = f"{naming}:{graph_weights}:{st.st_mtime_ns}:{st.st_size}"
    else:
        key = (naming + ":"
               + hashlib.sha256(graph_weights.encode()).hexdigest())
    if key not in _QUANT_CACHE:
        params = list_to_params(model, resolve_weights(graph_weights))
        _QUANT_CACHE[key] = quantize_params(params)
        while len(_QUANT_CACHE) > _QUANT_CACHE_MAX:
            _QUANT_CACHE.popitem(last=False)
    else:
        _QUANT_CACHE.move_to_end(key)
    return _QUANT_CACHE[key]


def predict_func(rows: Iterable, graph_json: str, prediction: str,
                 graph_weights: str, inp: str, activation: str, tf_input: str,
                 tf_dropout: Optional[str] = None, to_keep_dropout: bool = False,
                 chunk_size: int = 4096, extra_cols: Optional[List[str]] = None,
                 extra_inputs: Optional[List[str]] = None,
                 quantize: Optional[str] = None,
                 mesh_axes: Optional[Dict[str, int]] = None) -> List:
    """Per-partition inference (same signature/meaning as
    ``sparkflow/ml_util.py:54``). ``activation`` is the output tensor name.
    ``extra_cols``/``extra_inputs`` feed additional columns to additional
    tensors (multi-input models, e.g. an attention mask). ``quantize``
    serves int8 weights ('weight_only' or 'dynamic', ``utils/quant.py``);
    ``mesh_axes`` (e.g. ``{'dp': 8}``) serves over a device mesh."""
    if bool(extra_cols) != bool(extra_inputs) or (
            extra_cols and len(extra_cols) != len(extra_inputs)):
        raise ValueError("extra_cols and extra_inputs must pair up one-to-one")
    row_dicts = [r.asDict() for r in rows]
    if not row_dicts:
        return []
    dropout_v = 1.0 if (tf_dropout is not None and to_keep_dropout) else 0.0
    names = [tf_input] + list(extra_inputs) if extra_cols else tf_input
    model, fn = _cached_predict_fn(graph_json, activation, names,
                                   tf_dropout, dropout_v, quantize, mesh_axes)
    if quantize:
        params = _cached_quantized_params(model, graph_weights, quantize)
    else:
        params = list_to_params(model, resolve_weights(graph_weights))
    cols = [inp] + list(extra_cols) if extra_cols else [inp]
    stacked = tuple(
        np.stack([vector_to_array(rd[c]) for rd in row_dicts]).astype(np.float32)
        for c in cols)
    x = stacked if extra_cols else stacked[0]
    preds = predict_in_chunks(fn, params, x, chunk_size)
    for rd, p in zip(row_dicts, preds):
        arr = np.asarray(p)
        if arr.ndim == 0 or arr.size == 1:
            rd[prediction] = float(arr.reshape(()))
        else:
            rd[prediction] = Vectors.dense(arr)
    return [Row(**rd) for rd in row_dicts]


# ---------------------------------------------------------------------------
# Data marshalling (reference ml_util.py:86-134)
# ---------------------------------------------------------------------------


def handle_features(data: Iterable, is_supervised: bool = False
                    ) -> Tuple[Any, Optional[np.ndarray]]:
    """Materialize an iterator of (features, label) / features into arrays.
    Scalar labels wrap to ``[y]`` (reference ``ml_util.py:86-101``). A row's
    features may be a TUPLE of vectors (multi-input models); the return is
    then a matching tuple of arrays."""
    def to_arr(x):
        return x if isinstance(x, np.ndarray) else vector_to_array(x)

    features, labels = [], []
    multi = False
    for item in data:
        if is_supervised:
            x, y = item
            if isinstance(y, (int, float)):
                labels.append([y])
            else:
                labels.append(vector_to_array(y))
        else:
            x = item
        if isinstance(x, tuple):
            multi = True
            features.append([to_arr(c) for c in x])
        else:
            features.append(to_arr(x))
    if multi:
        f = tuple(np.asarray([row[i] for row in features], dtype=np.float32)
                  for i in range(len(features[0])))
    else:
        f = np.asarray(features, dtype=np.float32)
    l = np.asarray(labels, dtype=np.float32) if is_supervised else None
    return f, l


def handle_shuffle(features: np.ndarray, labels: Optional[np.ndarray]
                   ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    idx = np.random.permutation(features.shape[0])
    return features[idx], labels[idx] if labels is not None else None
