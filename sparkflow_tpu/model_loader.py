"""Pre-trained model import (reference ``sparkflow/tensorflow_model_loader.py``).

The reference imports TF1 ``Saver`` checkpoints into a ``SparkAsyncDLModel``
(``tensorflow_model_loader.py:8-32``). Here the native checkpoint formats are
JAX-ecosystem ones — ``.npz`` flat weight lists and orbax checkpoints — plus an
optional TF1-checkpoint path that activates only if TensorFlow happens to be
installed (it is not required by this framework).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .ml_util import convert_weights_to_json
from .spark_async import SparkAsyncDLModel


def _weights_from_npz(path: str) -> List[np.ndarray]:
    with np.load(path) as z:
        return [z[k] for k in sorted(z.files, key=lambda s: int(s.split("_")[-1]))]


def save_weights_npz(path: str, weights: List[np.ndarray]) -> None:
    """Save a flat weight list as ``.npz`` (keys ``w_0..w_{n-1}`` keep order)."""
    np.savez(path, **{f"w_{i}": w for i, w in enumerate(weights)})


def load_checkpoint_model(checkpoint_path: str,
                          graph_json: str,
                          inputCol: str,
                          tfInput: str,
                          tfOutput: str,
                          predictionCol: str = "predicted",
                          tfDropout: Optional[str] = None,
                          toKeepDropout: bool = False) -> SparkAsyncDLModel:
    """Load saved weights (npz or orbax dir) + a graph spec into a fitted
    ``SparkAsyncDLModel`` — the JAX-native equivalent of the reference's
    ``load_tensorflow_model`` (``tensorflow_model_loader.py:8-32``)."""
    from .models import model_from_json
    model = model_from_json(graph_json)
    if os.path.isdir(checkpoint_path):
        from .checkpoint import CheckpointManager
        weights = CheckpointManager.load_weights(checkpoint_path, model)
    else:
        weights = _weights_from_npz(checkpoint_path)
    # validate against the graph before wrapping
    from .graphdef import list_to_params
    list_to_params(model, weights)
    return SparkAsyncDLModel(
        inputCol=inputCol,
        modelJson=graph_json,
        modelWeights=convert_weights_to_json(weights),
        tfInput=tfInput,
        tfOutput=tfOutput,
        tfDropout=tfDropout,
        toKeepDropout=toKeepDropout,
        predictionCol=predictionCol)


def load_tensorflow_model(path: str,
                          inputCol: str,
                          tfInput: str,
                          tfOutput: str,
                          predictionCol: str = "predicted",
                          tfDropout: Optional[str] = None,
                          toKeepDropout: bool = False):
    """Import a TF1 Saver checkpoint's trainable variables (requires an
    installed TensorFlow AND a graph re-expressed in the nn DSL: TF1 protobuf
    graphs are not executable here). Provided for weight migration only."""
    try:
        import tensorflow as tf  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "load_tensorflow_model needs TensorFlow installed to read TF1 "
            "checkpoints; for native checkpoints use load_checkpoint_model "
            "(npz/orbax)") from e
    raise NotImplementedError(
        "TF1 MetaGraphDef graphs cannot execute on this framework; rebuild the "
        "model with sparkflow_tpu.nn and import the weights via "
        "load_checkpoint_model(save_weights_npz(...)).")


def attach_pretrained_model_to_pipeline(checkpoint_path: str, graph_json: str,
                                        pipeline_model, inputCol: str,
                                        tfInput: str, tfOutput: str,
                                        predictionCol: str = "predicted"):
    """Append an imported model to an existing PipelineModel (reference
    ``attach_tensorflow_model_to_pipeline``, ``tensorflow_model_loader.py:35-45``)."""
    from .compat import PipelineModel
    model = load_checkpoint_model(checkpoint_path, graph_json, inputCol,
                                  tfInput, tfOutput, predictionCol)
    return PipelineModel(stages=list(pipeline_model.stages) + [model])


# reference-named alias (same role; native checkpoint formats)
attach_tensorflow_model_to_pipeline = attach_pretrained_model_to_pipeline
