"""Fleet-scale trace-driven simulation: what-if the fleet, not the model.

The north-star system serves heavy traffic from a large fleet — but every
routing/health/canary policy question ("what does the pick rule do to a
mixed int8/bf16 fleet at 4x burst?") is unanswerable on a 3-replica test
rig and unaffordable to answer in production. This package answers them
offline: a deterministic discrete-event simulator
(:class:`~sparkflow_tpu.sim.core.FleetSimulator`) replays a request trace
(:mod:`~sparkflow_tpu.sim.trace`) against a modelled fleet whose
*decisions* are made by the real serving plane's policy code
(:mod:`sparkflow_tpu.serving.policies`, plus the real ``CircuitBreaker``,
``TokenBucket``, ``CanaryController``, and ``RetryPolicy`` on a virtual
clock) while transport + compute are priced by a bench-fitted
:class:`~sparkflow_tpu.sim.costmodel.CostModel`. Calibration
(:mod:`~sparkflow_tpu.sim.calibrate`) pins sim-vs-real agreement on the
same trace; determinism is byte-exact (same trace + seed => identical
event-log sha256).

See ``docs/sim.md``; ``make sim-smoke`` runs a 1000-replica x 1M-request
what-if end to end; ``bench.py --sim`` records scale + calibration
numbers in ``BENCH_NOTES.md``.
"""

from .core import (FleetSimulator, ReplicaSpec, SimAutoscaler, SimReplica,
                   SimReport, legacy_generate_pick_key)
from .costmodel import CostModel
from .trace import Request, load, save, synthetic_trace

# NOTE: `calibrate` is deliberately NOT imported here — it pulls the full
# serving stack (and through it JAX); `from sparkflow_tpu.sim import
# calibrate` loads it on demand. Pure-sim runs stay import-light.
__all__ = ["FleetSimulator", "ReplicaSpec", "SimAutoscaler", "SimReplica",
           "SimReport", "legacy_generate_pick_key", "CostModel", "Request",
           "synthetic_trace", "save", "load"]
