"""Optimizer registry: the reference's 10 named optimizers on optax.

The reference maps names to ``tf.train.*Optimizer`` classes
(``sparkflow/tensorflow_async.py:19-42``): adam, rmsprop, momentum, adadelta,
adagrad, gradient_descent, adagrad_da, ftrl, proximal_adagrad,
proximal_gradient_descent — with TF1 keyword options parsed from a JSON string
Param. Here the same names and option keys produce ``optax.GradientTransformation``s;
the four optimizers optax lacks (ftrl, adagrad_da, proximal_adagrad,
proximal_gradient_descent) are implemented below as custom transforms following the
TF1 update rules. All updates run inside the jitted train step, compiled by XLA —
there is no parameter-server-side optimizer process (reference
``sparkflow/HogwildSparkModel.py:190-196``).

Behavior parity notes:
- unknown optimizer names fall back to gradient_descent, as the reference does
  (``sparkflow/tensorflow_async.py:40-42``);
- ``use_locking`` is accepted and ignored: synchronous all-reduce replaces the
  Hogwild parameter server, so there is no shared mutable state to lock;
- ``momentum`` defaults its momentum to 0.9 when no options are given, matching
  ``sparkflow/tensorflow_async.py:36-38``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax


# ---------------------------------------------------------------------------
# Custom transforms for the TF1 optimizers optax does not ship
# ---------------------------------------------------------------------------


class FtrlState(NamedTuple):
    n: optax.Updates  # sum of squared gradients
    z: optax.Updates  # ftrl dual variable


def ftrl(learning_rate: float = 0.001, learning_rate_power: float = -0.5,
         initial_accumulator_value: float = 0.1,
         l1_regularization_strength: float = 0.0,
         l2_regularization_strength: float = 0.0) -> optax.GradientTransformation:
    """FTRL-Proximal (McMahan et al.), TF1 ``tf.train.FtrlOptimizer`` semantics."""
    lr = learning_rate
    p = -learning_rate_power  # TF convention: power is negative; p > 0
    l1 = l1_regularization_strength
    l2 = l2_regularization_strength

    def init_fn(params):
        return FtrlState(
            n=jax.tree.map(lambda t: jnp.full_like(t, initial_accumulator_value), params),
            z=jax.tree.map(jnp.zeros_like, params))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params")

        def per_leaf(g, n, z, w):
            n_new = n + jnp.square(g)
            sigma = (jnp.power(n_new, p) - jnp.power(n, p)) / lr
            z_new = z + g - sigma * w
            w_new = jnp.where(
                jnp.abs(z_new) <= l1,
                jnp.zeros_like(w),
                -(z_new - jnp.sign(z_new) * l1) / (jnp.power(n_new, p) / lr + 2.0 * l2))
            return w_new - w, n_new, z_new

        out = jax.tree.map(per_leaf, grads, state.n, state.z, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        n = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        z = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, FtrlState(n=n, z=z)

    return optax.GradientTransformation(init_fn, update_fn)


class AdagradDAState(NamedTuple):
    step: chex.Array
    g_acc: optax.Updates
    gg_acc: optax.Updates


def adagrad_da(learning_rate: float = 0.001,
               initial_gradient_squared_accumulator_value: float = 0.1,
               l1_regularization_strength: float = 0.0,
               l2_regularization_strength: float = 0.0) -> optax.GradientTransformation:
    """Adagrad Dual Averaging (Xiao 2010), TF1 ``tf.train.AdagradDAOptimizer``."""
    lr = learning_rate
    l1 = l1_regularization_strength
    l2 = l2_regularization_strength

    def init_fn(params):
        return AdagradDAState(
            step=jnp.zeros([], jnp.int32),
            g_acc=jax.tree.map(jnp.zeros_like, params),
            gg_acc=jax.tree.map(
                lambda t: jnp.full_like(t, initial_gradient_squared_accumulator_value),
                params))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("adagrad_da requires params")
        t = (state.step + 1).astype(jnp.float32)

        def per_leaf(g, ga, gg, w):
            ga_new = ga + g
            gg_new = gg + jnp.square(g)
            clipped = jnp.sign(ga_new) * jnp.maximum(jnp.abs(ga_new) - l1 * t, 0.0)
            w_new = -lr * clipped / (jnp.sqrt(gg_new) + l2 * t * lr)
            return w_new - w, ga_new, gg_new

        out = jax.tree.map(per_leaf, grads, state.g_acc, state.gg_acc, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        ga = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        gg = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, AdagradDAState(step=state.step + 1, g_acc=ga, gg_acc=gg)

    return optax.GradientTransformation(init_fn, update_fn)


class ProximalAdagradState(NamedTuple):
    accum: optax.Updates


def _prox(w, step_size, l1, l2):
    """Proximal operator for l1/l2 regularization (TF1 proximal_* semantics)."""
    shrunk = jnp.sign(w) * jnp.maximum(jnp.abs(w) - step_size * l1, 0.0)
    return shrunk / (1.0 + step_size * l2)


def proximal_adagrad(learning_rate: float = 0.001,
                     initial_accumulator_value: float = 0.1,
                     l1_regularization_strength: float = 0.0,
                     l2_regularization_strength: float = 0.0) -> optax.GradientTransformation:
    lr = learning_rate
    l1 = l1_regularization_strength
    l2 = l2_regularization_strength

    def init_fn(params):
        return ProximalAdagradState(
            accum=jax.tree.map(lambda t: jnp.full_like(t, initial_accumulator_value), params))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("proximal_adagrad requires params")

        def per_leaf(g, a, w):
            a_new = a + jnp.square(g)
            step = lr / jnp.sqrt(a_new)
            w_new = _prox(w - step * g, step, l1, l2)
            return w_new - w, a_new

        out = jax.tree.map(per_leaf, grads, state.accum, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        accum = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, ProximalAdagradState(accum=accum)

    return optax.GradientTransformation(init_fn, update_fn)


def proximal_gradient_descent(learning_rate: float = 0.001,
                              l1_regularization_strength: float = 0.0,
                              l2_regularization_strength: float = 0.0) -> optax.GradientTransformation:
    lr = learning_rate
    l1 = l1_regularization_strength
    l2 = l2_regularization_strength

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("proximal_gradient_descent requires params")
        updates = jax.tree.map(lambda g, w: _prox(w - lr * g, lr, l1, l2) - w, grads, params)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Registry / factory
# ---------------------------------------------------------------------------


def _pop(options: Dict[str, Any], *names, default=None):
    for n in names:
        if n in options:
            return options.pop(n)
    return default


def build_optimizer(optimizer_name: str, learning_rate: Optional[float] = None,
                    optimizer_options: Optional[Dict[str, Any]] = None
                    ) -> optax.GradientTransformation:
    """Name + TF1-style options dict -> optax transformation.

    Mirrors the reference factory (``sparkflow/tensorflow_async.py:17-42``):
    when ``optimizer_options`` is None, uses ``learning_rate`` with TF-like
    defaults; unknown names fall back to gradient_descent.
    """
    opts = dict(optimizer_options or {})
    opts.pop("use_locking", None)
    lr = _pop(opts, "learning_rate", default=learning_rate if learning_rate is not None else 0.001)
    schedule = opts.pop("schedule", None)     # upgrade: LR schedules (below)
    accum = int(opts.pop("grad_accum_steps", 0) or 0)
    # upgrade keys: gradient clipping (applied to the raw gradient, BEFORE
    # the optimizer sees it) and decoupled weight decay (AdamW-style,
    # applied with the update — multiplied by the lr inside optax)
    clip_norm = opts.pop("clip_norm", None)
    clip_value = opts.pop("clip_value", None)
    weight_decay = float(opts.pop("weight_decay", 0.0) or 0.0)
    ema_decay = float(opts.pop("ema_decay", 0.0) or 0.0)

    base = _build_base_optimizer(optimizer_name, lr, opts)
    if weight_decay > 0.0:
        # DECOUPLED decay (Loshchilov & Hutter): -lr*wd*param added to the
        # final update, OUTSIDE any adaptive preconditioning — chaining
        # add_decayed_weights before the optimizer would be plain L2 run
        # through e.g. adam's rescaling, a different (worse) method
        base = _with_decoupled_decay(base, weight_decay, lr)
    pre = []
    if clip_value is not None:
        pre.append(optax.clip(float(clip_value)))
    if clip_norm is not None:
        pre.append(optax.clip_by_global_norm(float(clip_norm)))
    if pre:
        base = optax.chain(*pre, base)
    if accum > 1:
        # gradient accumulation: optax.MultiSteps applies the update every
        # `accum` mini-steps with the averaged gradient — large effective
        # batch without the HBM for it; state checkpoints like any pytree
        base = optax.MultiSteps(base, every_k_schedule=accum)
    if schedule is not None:
        # RELATIVE schedule: scales the applied update (== scaling lr for the
        # optax optimizers; for the closed-form TF1 variants it scales the
        # final delta). Chained OUTSIDE MultiSteps so the schedule counts
        # MINI-steps — warmup_steps/decay_steps mean Trainer batches whether
        # or not accumulation is on (on skipped mini-steps it scales a zero
        # update, a no-op).
        base = optax.chain(base, optax.scale_by_schedule(
            build_schedule(schedule)))
    if ema_decay:  # any nonzero value validates — including sign typos
        if not (0.0 < ema_decay < 1.0):
            # 1.0 would freeze the zeros-init average (and debias it into
            # an all-zeros tree); >1 or negative diverges — fail at build,
            # not after a full fit
            raise ValueError(
                f"ema_decay must be in (0, 1), got {ema_decay}")
        # OUTERMOST so the EMA tracks the post-update weights the run
        # actually applies (after decay/clip/accumulation/schedule); under
        # accumulation the wrapper skips the zero-update mini-steps, so
        # the decay means per APPLIED update regardless of grad_accum_steps
        base = _with_weight_ema(base, ema_decay,
                                skip_zero_updates=accum > 1)
    return base


def _with_decoupled_decay(inner: optax.GradientTransformation,
                          weight_decay: float,
                          lr: float) -> optax.GradientTransformation:
    """Add ``-lr * weight_decay * param`` to the inner update (AdamW-style
    decoupled decay, valid for any base optimizer). Requires ``params`` at
    update time — every train step in this framework passes them."""
    def init(params):
        return inner.init(params)

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("weight_decay needs params at update time")
        u, s = inner.update(updates, state, params)
        u = jax.tree.map(lambda du, p: du - lr * weight_decay * p, u, params)
        return u, s

    return optax.GradientTransformation(init, update)


class WeightEmaState(NamedTuple):
    inner: Any
    ema: optax.Params
    count: jax.Array
    decay: jax.Array  # baked into state so extraction needs no config


def _with_weight_ema(inner: optax.GradientTransformation,
                     decay: float,
                     skip_zero_updates: bool = False
                     ) -> optax.GradientTransformation:
    """Maintain an exponential moving average of the POST-update weights in
    optimizer state (Polyak averaging — the standard serving-quality
    upgrade). ``extract_ema_params(opt_state)`` recovers the debiased
    averaged tree; the training weights themselves are untouched."""
    def init(params):
        return WeightEmaState(inner=inner.init(params),
                              ema=jax.tree.map(jnp.zeros_like, params),
                              count=jnp.zeros((), jnp.int32),
                              decay=jnp.asarray(decay, jnp.float32))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("ema_decay needs params at update time")
        u, s = inner.update(updates, state.inner, params)
        new_p = optax.apply_updates(params, u)
        if skip_zero_updates:
            # Blend only on mini-steps whose applied update is nonzero:
            # under grad accumulation MultiSteps emits zero updates between
            # boundaries, and blending toward unchanged params on those
            # would shrink the configured averaging horizon by the
            # accumulation factor. (An exactly-zero REAL update also skips
            # — measure-zero in fp training and harmless.) Without
            # accumulation the gate can never fire, so the O(params)
            # reduction is skipped entirely.
            changed = jnp.asarray(
                sum(jnp.sum(jnp.abs(x)) for x in jax.tree.leaves(u)) > 0,
                jnp.float32)
        else:
            changed = jnp.ones((), jnp.float32)
        d_eff = 1.0 - (1.0 - state.decay) * changed
        ema = jax.tree.map(
            lambda e, p: d_eff * e + (1.0 - d_eff) * p, state.ema, new_p)
        return u, WeightEmaState(inner=s, ema=ema,
                                 count=state.count + changed.astype(jnp.int32),
                                 decay=state.decay)

    return optax.GradientTransformation(init, update)


def extract_ema_params(opt_state):
    """The debiased EMA weight tree from an ``ema_decay``-enabled optimizer
    state, or None when EMA isn't enabled. Searches through wrapper states
    (MultiSteps, chains) for the :class:`WeightEmaState`; debiasing divides
    by ``1 - decay^count`` (the zeros-init underestimate, like Adam's
    moment correction)."""
    def find(s):
        if isinstance(s, WeightEmaState):
            return s
        if isinstance(s, (tuple, list)):  # optax wrapper states are all
            for child in s:               # NamedTuples — tuple traversal
                got = find(child)         # covers them
                if got is not None:
                    return got
        return None

    st = find(opt_state)
    if st is None or int(st.count) == 0:
        # never updated (e.g. a zero-epoch fit): the zeros-init ema would
        # debias to an all-zeros weight tree — None matches the documented
        # "not populated" contract instead of serving garbage
        return None
    corr = 1.0 - jnp.power(st.decay, st.count.astype(jnp.float32))
    return jax.tree.map(lambda e: e / jnp.maximum(corr, 1e-12), st.ema)


def build_schedule(cfg) -> optax.Schedule:
    """JSON-friendly schedule spec -> optax schedule of RELATIVE lr factors
    (1.0 = the optimizer's configured learning rate).

    ``{"type": "warmup_cosine", "warmup_steps": W, "decay_steps": D,
       "end_factor": a}``  — linear 0->1 over W, cosine 1->a over D
    ``{"type": "cosine", "decay_steps": D, "end_factor": a}``
    ``{"type": "linear", "decay_steps": D, "end_factor": a}``
    ``{"type": "exponential", "decay_steps": D, "decay_rate": r}``
    ``{"type": "warmup", "warmup_steps": W}``
    """
    if callable(cfg):
        return cfg
    if isinstance(cfg, str):
        cfg = {"type": cfg}  # shorthand: "cosine" == {"type": "cosine"}
    if not isinstance(cfg, dict):
        raise ValueError(
            f"schedule spec must be a dict like {{'type': 'warmup_cosine', "
            f"...}}, a type name string, or a callable; got {cfg!r}")
    kind = cfg.get("type", "warmup_cosine")
    warm = int(cfg.get("warmup_steps", 0))
    decay = int(cfg.get("decay_steps", 0))
    end = float(cfg.get("end_factor", 0.0))
    if kind == "warmup":
        return optax.linear_schedule(0.0, 1.0, max(1, warm))
    if kind == "linear":
        return optax.linear_schedule(1.0, end, max(1, decay))
    if kind == "exponential":
        return optax.exponential_decay(1.0, max(1, decay),
                                       float(cfg.get("decay_rate", 0.96)))
    if kind == "cosine":
        return optax.cosine_decay_schedule(1.0, max(1, decay), alpha=end)
    if kind == "warmup_cosine":
        if not warm:
            return optax.cosine_decay_schedule(1.0, max(1, decay), alpha=end)
        return optax.warmup_cosine_decay_schedule(
            0.0, 1.0, warm, max(warm + 1, warm + decay), end_value=end)
    raise ValueError(f"unknown schedule type {kind!r}; known: warmup, "
                     f"linear, exponential, cosine, warmup_cosine")


def _build_base_optimizer(optimizer_name: str, lr, opts
                          ) -> optax.GradientTransformation:

    if optimizer_name == "adam":
        # mu_dtype='bfloat16' halves the first-moment HBM (the second moment
        # and params stay f32) — the standard large-model memory lever; the
        # update math still runs f32 (optax upcasts mu before use)
        mu_dtype = _pop(opts, "mu_dtype", default=None)
        # betas pinned to f32: optax's bias correction computes decay**count,
        # and a Python-float decay is a weak f64 under x64 — the pow would
        # silently promote the correction (graftcheck GC-J103)
        return optax.adam(lr,
                          b1=np.float32(_pop(opts, "beta1", "b1", default=0.9)),
                          b2=np.float32(_pop(opts, "beta2", "b2", default=0.999)),
                          eps=_pop(opts, "epsilon", "eps", default=1e-8),
                          mu_dtype=mu_dtype)
    if optimizer_name == "rmsprop":
        return optax.rmsprop(lr, decay=_pop(opts, "decay", default=0.9),
                             eps=_pop(opts, "epsilon", "eps", default=1e-10),
                             centered=bool(_pop(opts, "centered", default=False)),
                             momentum=_pop(opts, "momentum", default=0.0))
    if optimizer_name == "momentum":
        return optax.sgd(lr, momentum=_pop(opts, "momentum", default=0.9),
                         nesterov=bool(_pop(opts, "use_nesterov", default=False)))
    if optimizer_name == "adadelta":
        return optax.adadelta(lr, rho=_pop(opts, "rho", default=0.95),
                              eps=_pop(opts, "epsilon", "eps", default=1e-8))
    if optimizer_name == "adagrad":
        return optax.adagrad(lr, initial_accumulator_value=_pop(
            opts, "initial_accumulator", "initial_accumulator_value", default=0.1))
    if optimizer_name == "ftrl":
        return ftrl(lr,
                    learning_rate_power=_pop(opts, "learning_rate_power", default=-0.5),
                    initial_accumulator_value=_pop(opts, "initial_accumulator_value", default=0.1),
                    l1_regularization_strength=_pop(opts, "l1_regularization_strength", default=0.0),
                    l2_regularization_strength=_pop(opts, "l2_regularization_strength", default=0.0))
    if optimizer_name == "adagrad_da":
        return adagrad_da(lr,
                          initial_gradient_squared_accumulator_value=_pop(
                              opts, "initial_gradient_squared_accumulator_value", default=0.1),
                          l1_regularization_strength=_pop(opts, "l1_regularization_strength", default=0.0),
                          l2_regularization_strength=_pop(opts, "l2_regularization_strength", default=0.0))
    if optimizer_name == "proximal_adagrad":
        return proximal_adagrad(lr,
                                initial_accumulator_value=_pop(opts, "initial_accumulator_value", default=0.1),
                                l1_regularization_strength=_pop(opts, "l1_regularization_strength", default=0.0),
                                l2_regularization_strength=_pop(opts, "l2_regularization_strength", default=0.0))
    if optimizer_name == "proximal_gradient_descent":
        return proximal_gradient_descent(lr,
                                         l1_regularization_strength=_pop(opts, "l1_regularization_strength", default=0.0),
                                         l2_regularization_strength=_pop(opts, "l2_regularization_strength", default=0.0))
    # gradient_descent + unknown-name fallback (reference behavior)
    return optax.sgd(lr)


AVAILABLE_OPTIMIZERS = (
    "adam", "rmsprop", "momentum", "adadelta", "adagrad", "gradient_descent",
    "adagrad_da", "ftrl", "proximal_adagrad", "proximal_gradient_descent",
)

# name -> ctor(learning_rate=...) with registry defaults; the form
# optax.inject_hyperparams needs for vmapped hyperparameter sweeps
# (parallel/hyper.py). Unknown names fall back to sgd there, matching
# build_optimizer's reference-parity fallback above.
OPTIMIZER_BUILDERS = {
    "adam": optax.adam,
    "rmsprop": optax.rmsprop,
    "momentum": lambda learning_rate: optax.sgd(learning_rate, momentum=0.9),
    "adadelta": optax.adadelta,
    "adagrad": optax.adagrad,
    "gradient_descent": optax.sgd,
    "ftrl": ftrl,
    "adagrad_da": adagrad_da,
    "proximal_adagrad": proximal_adagrad,
    "proximal_gradient_descent": proximal_gradient_descent,
}


def build_optimizer_from_json(optimizer_name: str, learning_rate: Optional[float],
                              optimizer_options_json: Optional[str]) -> optax.GradientTransformation:
    opts = json.loads(optimizer_options_json) if optimizer_options_json else None
    return build_optimizer(optimizer_name, learning_rate, opts)


# ZeRO-1 weight-update sharding lives in its own module to keep this one a
# pure registry; re-exported here so "wrap any registry optimizer" reads as
# one import site (see optimizers_sharded for layout + checkpoint interop).
from .optimizers_sharded import (  # noqa: E402
    sharded_update,
    zero1_state_specs,
    place_zero1_state,
    gather_zero1_state,
    shard_zero1_state,
    has_per_param_state,
)
