"""Dense/sparse vectors, API-compatible with ``pyspark.ml.linalg``.

The reference consumes feature columns of Spark ML vectors (dense or sparse —
``tests/dl_runner.py:164-185`` exercises ``Vectors.sparse``) and emits
``Vectors.dense`` predictions (``sparkflow/ml_util.py:74-81``).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


class DenseVector:
    __slots__ = ("values",)

    def __init__(self, values):
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        return self.values

    @property
    def size(self) -> int:
        return self.values.shape[0]

    def __len__(self):
        return self.values.shape[0]

    def __getitem__(self, i):
        return self.values[i]

    def __iter__(self):
        return iter(self.values)

    def __eq__(self, other):
        if isinstance(other, (DenseVector, SparseVector)):
            return np.array_equal(self.toArray(), other.toArray())
        return NotImplemented

    def __repr__(self):
        return f"DenseVector({self.values.tolist()})"


class SparseVector:
    __slots__ = ("_size", "indices", "values")

    def __init__(self, size: int, indices, values=None):
        if values is None and isinstance(indices, dict):
            items = sorted(indices.items())
            indices = [i for i, _ in items]
            values = [v for _, v in items]
        self._size = int(size)
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values, dtype=np.float64)

    @property
    def size(self) -> int:
        return self._size

    def toArray(self) -> np.ndarray:
        arr = np.zeros(self._size, dtype=np.float64)
        arr[self.indices] = self.values
        return arr

    def __len__(self):
        return self._size

    def __getitem__(self, i):
        pos = np.searchsorted(self.indices, i)
        if pos < len(self.indices) and self.indices[pos] == i:
            return self.values[pos]
        return 0.0

    def __eq__(self, other):
        if isinstance(other, (DenseVector, SparseVector)):
            return np.array_equal(self.toArray(), other.toArray())
        return NotImplemented

    def __repr__(self):
        return (f"SparseVector({self._size}, {self.indices.tolist()}, "
                f"{self.values.tolist()})")


class Vectors:
    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return DenseVector(values)

    @staticmethod
    def sparse(size: int, indices, values=None) -> SparseVector:
        return SparseVector(size, indices, values)


def vector_to_array(v) -> np.ndarray:
    """Coerce any supported feature value (localml or pyspark vector, list,
    ndarray, scalar) to a 1-D float array."""
    if hasattr(v, "toArray"):
        return np.asarray(v.toArray(), dtype=np.float64)
    if isinstance(v, (list, tuple, np.ndarray)):
        return np.asarray(v, dtype=np.float64)
    return np.asarray([v], dtype=np.float64)
