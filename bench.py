"""Headline benchmark: MNIST CNN training throughput (examples/sec) on TPU.

Config matches BASELINE.md's primary metric — the reference's
``examples/cnn_example.py`` model trained via the framework — against the
measured single-node Hogwild-proxy baseline in ``BASELINE_MEASURED.json``
(see ``bench_baseline.py``; the reference publishes no numbers of its own).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
# Any successful TPU measurement is persisted here so that a later run — e.g.
# the end-of-round driver invocation — can still report a real TPU number if
# the relay has wedged in the meantime (it can hang for hours; see
# sparkflow_tpu/utils/hw.py). The cache is only ever written from an actual
# TPU run and the note always says when the number was captured.
TPU_CACHE = os.path.join(_HERE, "BENCH_TPU_CACHE.json")


def _load_baseline():
    """Current baseline ex/s from BASELINE_MEASURED.json, or None."""
    path = os.path.join(_HERE, "BASELINE_MEASURED.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)["baseline_examples_per_sec"]


def _load_cached_tpu_result():
    if not os.path.exists(TPU_CACHE):
        return None
    try:
        with open(TPU_CACHE) as f:
            cached = json.load(f)
        needed = ("metric", "value", "unit", "vs_baseline")
        if cached.get("platform") == "tpu" and all(k in cached for k in needed):
            return cached
    except (ValueError, OSError):
        pass
    return None


def main():
    from sparkflow_tpu.utils.hw import (enable_compilation_cache,
                                        ensure_live_backend)

    # Bounded retry: a transient relay hiccup shouldn't demote the round's
    # artifact to a CPU number. Two probes, short backoff, then fall back.
    fell_back = ensure_live_backend(retries=2, backoff_s=20)
    # persistent XLA cache: repeat bench invocations skip the 20-40s compile
    enable_compilation_cache()

    import jax

    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.trainer import Trainer
    from sparkflow_tpu.parallel.mesh import default_mesh

    quick = "--quick" in sys.argv or fell_back  # CPU fallback: smallest honest run
    fallback = fell_back

    if fallback:
        cached = _load_cached_tpu_result()
        if cached is not None:
            # machine-readable staleness markers alongside the note: the
            # number was produced by an earlier commit's full-size TPU run,
            # reported because the relay is wedged NOW (a CPU number would
            # misrepresent TPU throughput far worse)
            # recompute the ratio against the CURRENT baseline file — the
            # baseline may have been re-measured since the capture
            base = _load_baseline()
            vs = (round(cached["value"] / base, 2) if base
                  else cached["vs_baseline"])
            out = {
                "metric": cached["metric"],
                "value": cached["value"],
                "unit": cached["unit"],
                "vs_baseline": vs,
                **{k: cached[k] for k in
                   ("tflops_per_sec", "mfu", "runs") if k in cached},
                "stale": True,
                "measured_at_commit": cached.get("commit", "unknown"),
                "note": ("tpu relay wedged at bench time; reporting TPU "
                         "measurement captured %s at commit %s (full-size "
                         "run; see BENCH_TPU_CACHE.json)"
                         % (cached.get("captured_at", "earlier this round"),
                            cached.get("commit", "unknown"))),
            }
            print(json.dumps(out))
            return

    def cnn_model():
        x = nn.placeholder([None, 784], name="x")
        y = nn.placeholder([None, 10], name="y")
        xr = nn.reshape(x, [-1, 28, 28, 1])
        c1 = nn.conv2d(xr, 32, 5, activation="relu")
        p1 = nn.max_pooling2d(c1, 2, 2)
        c2 = nn.conv2d(p1, 64, 3, activation="relu")
        p2 = nn.max_pooling2d(c2, 2, 2)
        out = nn.dense(nn.flatten(p2), 10, name="out")
        nn.softmax_cross_entropy(y, out)

    mg = build_graph(cnn_model)

    n = (1024 if fallback else 4096) if quick else 16384
    rs = np.random.RandomState(0)
    x = rs.rand(n, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n)]

    platform = jax.devices()[0].platform
    # bf16 compute on TPU (f32 accumulation stays on); f32 elsewhere
    compute_dtype = "bfloat16" if platform == "tpu" else None

    iters = 2 if quick else 6
    trainer = Trainer(mg, "x:0", "y:0", optimizer="adam",
                      optimizer_options={"learning_rate": 1e-3},
                      mini_batch_size=1024, shuffle_per_iter=True,
                      iters=iters, mesh=default_mesh(),
                      compute_dtype=compute_dtype)

    # warmup fit compiles the SAME fused multi-epoch program the measured
    # fit reuses (the whole fit is one device dispatch — see
    # core.make_multi_epoch_fn); measured run starts from its params
    trainer.fit(x, y)

    # median-of-3 (the warm/cold relay spread is ~1.6x — BENCH_NOTES.md):
    # single-run headlines are fragile, so the protocol lives in-code
    runs = 1 if quick else 3
    eps_runs = sorted(
        trainer.fit(x, y, init_params=trainer.params).examples_per_sec
        for _ in range(runs))
    eps = eps_runs[len(eps_runs) // 2]

    base = _load_baseline()
    vs_baseline = round(eps / base, 2) if base else None

    out = {
        "metric": "mnist_cnn_examples_per_sec",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": vs_baseline,
    }
    if runs > 1:
        out["runs"] = [round(e, 1) for e in eps_runs]
    # MFU accounting: XLA's own FLOPs count for one train step (the CNN is
    # pure XLA — no pallas custom calls to undercount), times steps/sec,
    # against the chip's bf16 peak
    from sparkflow_tpu.utils.flops import (device_peak_flops, mfu,
                                           train_step_flops)
    step_fl = train_step_flops(trainer.model, "x:0", "y:0",
                               trainer.optimizer, x[:1024], y[:1024])
    if step_fl:
        fps = (eps / 1024.0) * step_fl
        out["tflops_per_sec"] = round(fps / 1e12, 3)
        u = mfu(fps, device_peak_flops())
        if u is not None:
            out["mfu"] = round(u, 4)
    if fallback:
        out["note"] = (
            "tpu relay wedged at bench time (hung at backend init all "
            "round); measured on CPU fallback. Last successful TPU "
            "measurement: 51,229 ex/s = 17.8-18.8x baseline (round 1, this same "
            "benchmark before the relay outage — see BENCH_NOTES.md).")
    elif platform == "tpu" and not quick:
        # persist only FULL-SIZE TPU measurements, with provenance, so a
        # later wedged-relay run can report an honest earlier number
        import subprocess
        try:
            commit = subprocess.run(
                ["git", "-C", _HERE, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10).stdout.strip()
        except Exception:
            commit = "unknown"
        cache = dict(out, platform="tpu", commit=commit or "unknown",
                     captured_at=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime()))
        with open(TPU_CACHE, "w") as f:
            json.dump(cache, f, indent=1)
    print(json.dumps(out))


def span_overhead_main():
    """Micro-bench for the obs layer: run the same jitted train step in a
    tight loop with and without ``obs.span`` instrumentation and report the
    relative overhead. Prints ONE JSON line:
    {"metric": "span_overhead_pct", "value", "unit", "threshold_pct", "pass"}.

    The step is small but real — value_and_grad of an MSE through a
    (512,256)@(256,128) matmul plus an SGD update — so the denominator
    includes one genuine XLA dispatch per step, which is what a span wraps
    in practice.

    Methodology: the added work per traced step is exactly two span
    enter/exits (the outer per-step span plus one nested phase span, the
    shape ``Trainer.fit(trace_spans=True)`` emits), so that pair is timed
    in a tight loop where it is measurable to ~2% — and divided by the
    measured per-step time. A direct A/B difference of two ~1e2..1e3us
    step loops cannot resolve a sub-5% effect on a shared host (scheduler
    and frequency noise is itself +/-3-5% of the step at any size; in
    calibration it produced deltas from -4.6% to +9% for the same code),
    so the A/B delta is reported only as a diagnostic field.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from sparkflow_tpu.obs import Tracer

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(512, 256).astype(np.float32))
    w = jnp.asarray(rs.rand(256, 128).astype(np.float32) * 0.1)
    y = jnp.asarray(rs.rand(512, 128).astype(np.float32))

    @jax.jit
    def step(w):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)
        l, g = jax.value_and_grad(loss)(w)
        return w - 1e-3 * g, l

    # warm up the compile so neither loop pays it
    w2, l = step(w)
    jax.block_until_ready((w2, l))

    tr = Tracer()

    # (1) cost of the added instrumentation, isolated: one nested span pair
    # per iteration, exactly the per-step shape the traced loop below adds.
    # Tight-loop minima are stable to ~2% where A/B step-loop deltas are not.
    pair_iters = 50000

    def pair_loop():
        t0 = time.perf_counter()
        with tr.activate():
            for i in range(pair_iters):
                with tr.span("bench/step", args={"i": i}):
                    with tr.span("bench/compute"):
                        pass
        return (time.perf_counter() - t0) / pair_iters

    span_pair_s = min(pair_loop() for _ in range(3))

    # (2) per-step time of the real jitted loop, plain vs traced,
    # interleaved (the traced number feeds the diagnostic A/B delta only)
    seg = 50

    def plain_seg():
        wi = w
        t0 = time.perf_counter()
        for _ in range(seg):
            wi, li = step(wi)
            jax.block_until_ready(li)
        return (time.perf_counter() - t0) / seg

    def traced_seg():
        wi = w
        t0 = time.perf_counter()
        with tr.activate():
            for i in range(seg):
                with tr.span("bench/step", args={"i": i}):
                    with tr.span("bench/compute"):
                        wi, li = step(wi)
                        jax.block_until_ready(li)
        return (time.perf_counter() - t0) / seg

    plain, traced = 1e9, 1e9
    for _ in range(10):
        plain = min(plain, plain_seg())
        traced = min(traced, traced_seg())

    overhead_pct = span_pair_s / plain * 100.0

    out = {
        "metric": "span_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "threshold_pct": 5.0,
        "pass": overhead_pct < 5.0,
        "spans_per_step": 2,
        "span_pair_us": round(span_pair_s * 1e6, 3),
        "plain_step_us": round(plain * 1e6, 2),
        "ab_delta_pct_diagnostic": round((traced - plain) / plain * 100.0, 2),
    }
    print(json.dumps(out))


def trace_overhead_main():
    """Micro-bench for distributed tracing: the full per-request tracing
    kit (traceparent parse, request+dispatch spans with trace args, tail
    retention verdict, flight-recorder begin/end — i.e. everything PR 20
    adds to a served request) costed against a real batched predict.
    Prints ONE JSON line:
    {"metric": "trace_overhead_ratio", "value", "unit", "threshold", "pass"}.

    ``value`` is the throughput ratio tracing-on / tracing-off, derived as
    ``t_request / (t_request + t_kit)``: the baseline is a real HTTP
    request through ``InferenceServer`` + ``ServingClient`` with the
    tracer disabled (the deployment configuration tracing competes with),
    and the kit cost is a tight-loop minimum — the same methodology as
    ``--span-overhead``, because a direct A/B of two HTTP loops cannot
    resolve a sub-2% effect on a shared host (its delta is reported as a
    diagnostic field only). The pin is >= 0.98x, i.e. tracing may cost at
    most 2% of per-request throughput.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.obs import FlightRecorder, TraceCollector, Tracer
    from sparkflow_tpu.obs.spans import TraceContext
    from sparkflow_tpu.serving import (InferenceEngine, InferenceServer,
                                       ServingClient)
    from sparkflow_tpu.utils.metrics import Metrics

    def mlp():
        x = nn.placeholder([None, 16], name="x")
        h = nn.dense(x, 32, activation="relu")
        out = nn.dense(h, 8, name="out")
        nn.mean_squared_error(x, out)

    rs = np.random.RandomState(0)
    weights = [rs.randn(16, 32).astype(np.float32),
               rs.randn(32).astype(np.float32),
               rs.randn(32, 8).astype(np.float32),
               rs.randn(8).astype(np.float32)]
    x = rs.rand(2, 16).astype(np.float32).tolist()

    def serve(tracer):
        eng = InferenceEngine(build_graph(mlp), weights, input_name="x:0",
                              output_name="out/BiasAdd:0", max_batch=16)
        srv = InferenceServer(eng, max_delay_ms=0.0, memory_watch=False,
                              tracer=tracer)
        srv.start()
        return srv, ServingClient(srv.url)

    def request_loop(client, reps=3, iters=40):
        for _ in range(10):
            client.predict_full(x)             # warm compile + connection
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                client.predict_full(x)
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None or dt < best else best
        return best

    # (1) baseline request cost over real HTTP, tracer disabled
    srv_off, c_off = serve(Tracer(enabled=False))
    t_request = request_loop(c_off)
    srv_off.stop()

    # (2) the tracing kit, isolated in a tight loop where it resolves to
    # ~2%: exactly what one traced request adds across router + replica
    metrics = Metrics()
    tr = Tracer()
    collector = TraceCollector(tr, metrics=metrics, head_sample=0.0)
    flight_path = os.path.join(tempfile.mkdtemp(prefix="trace-bench-"),
                               "replica-0.jsonl")
    flight = FlightRecorder(flight_path, tracer=tr, metrics=metrics)
    header = TraceContext.mint().to_header()
    kit_iters = 3000
    budget = 8   # decode ticks per request: a traced generate records one
    #              post-hoc span per tick, so the kit charges for them too

    def kit_loop():
        t0 = time.perf_counter()
        with tr.activate():
            for _ in range(kit_iters):
                ctx = TraceContext.parse(header)
                flight.begin(ctx.trace_id)
                with tr.span("router/request",
                             args={"request_id": "r",
                                   "trace_id": ctx.trace_id}):
                    with tr.span("router/dispatch",
                                 args={"trace_id": ctx.trace_id,
                                       "replica": "u", "hedge": False}):
                        tick = time.perf_counter()
                        for _ in range(budget):
                            tr.record("serving/decode_tick", tick,
                                      tick, args={"trace_id": ctx.trace_id})
                flight.end(ctx.trace_id)
                collector.should_keep(1.0)
        return (time.perf_counter() - t0) / kit_iters

    t_kit = min(kit_loop() for _ in range(3))
    flight.close()

    # (3) diagnostic A/B: the same HTTP loop with tracing fully on
    srv_on, c_on = serve(tr)
    t_request_on = request_loop(c_on)
    srv_on.stop()

    ratio = t_request / (t_request + t_kit)
    out = {
        "metric": "trace_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "x (throughput, tracing-on / tracing-off)",
        "threshold": 0.98,
        "pass": ratio >= 0.98,
        "per_request_us": round(t_request * 1e6, 2),
        "trace_kit_us": round(t_kit * 1e6, 3),
        "ab_ratio_diagnostic": round(t_request / t_request_on, 4),
    }
    print(json.dumps(out))


def elastic_straggler_main():
    """Sync vs elastic DP under a deterministic 10x straggler. Prints ONE
    JSON line: {"metric": "elastic_dp_straggler_speedup", "value", ...}.

    Runs on the virtual-time engine (``parallel.elastic.run_virtual``):
    4 replicas with per-step costs [1, 1, 1, 10] simulated seconds train a
    small MLP for a fixed 60-virtual-second budget. The sync number is the
    ideal barrier bound on the same fleet (every step gated on the 10x
    replica, zero collective overhead — generous to sync), so the reported
    speedup is conservative and hardware-independent; the elastic number is
    what the fleet actually applied to the store inside the budget.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import optax

    import jax.numpy as jnp
    from sparkflow_tpu.parallel.elastic import (
        ElasticDPEngine, ReplicaSpec, sync_baseline_examples_per_sec)
    from sparkflow_tpu.utils.metrics import Metrics

    rs = np.random.RandomState(0)
    n, d, batch = 512, 16, 32
    X = rs.rand(n, d).astype(np.float32)
    W = rs.randn(d, 1).astype(np.float32)
    Y = X @ W + 0.01 * rs.randn(n, 1).astype(np.float32)

    def loss_fn(params, x, y, mask, rng):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2)

    params0 = {"w1": jnp.zeros((d, 16)), "b1": jnp.zeros((16,)),
               "w2": jnp.zeros((16, 1)), "b2": jnp.zeros((1,))}
    costs = [1.0, 1.0, 1.0, 10.0]
    shards = [(X[i::4], Y[i::4]) for i in range(4)]

    t0 = time.perf_counter()
    eng = ElasticDPEngine(loss_fn, optax.adam(0.01), params0,
                          max_staleness=4, metrics=Metrics())
    res = eng.run_virtual(shards, [ReplicaSpec(cost_s=c) for c in costs],
                          epochs=10_000, batch_size=batch, seed=0,
                          deadline_s=60.0)
    host_s = time.perf_counter() - t0

    sync_eps = sync_baseline_examples_per_sec(costs, batch)
    speedup = res.examples_per_sec / sync_eps
    out = {
        "metric": "elastic_dp_straggler_speedup",
        "value": round(speedup, 2),
        "unit": "x vs ideal sync barrier",
        "threshold": 3.0,
        "pass": speedup >= 3.0,
        "elastic_examples_per_vsec": round(res.examples_per_sec, 1),
        "sync_examples_per_vsec": round(sync_eps, 1),
        "straggler_factor": 10,
        "replicas": len(costs),
        "virtual_budget_s": 60.0,
        "pushes_accepted": res.stats["accepted"],
        "pushes_rejected_stale": res.stats["rejected_stale"],
        "host_wall_s": round(host_s, 2),
    }
    print(json.dumps(out))


def decode_throughput_main():
    """Continuous vs static batching for autoregressive decode. Prints ONE
    JSON line: {"metric": "decode_continuous_vs_static_speedup", ...}.

    Same DecodeEngine (paged KV cache + AOT fixed-shape decode step) under
    both schedulers, same mixed-length workload. Static batching admits
    ``num_slots`` requests at a time and runs the group until its LONGEST
    member finishes — the convoy cost. Continuous batching retires each
    sequence at its own token budget and refills the slot immediately.
    Tokens/sec counts USEFUL tokens only; per-token latency percentiles
    come from the engine's per-step ``serving/decode/token_latency_ms``
    histogram during the continuous run.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving.batcher import ContinuousBatcher
    from sparkflow_tpu.serving.decode import DecodeEngine
    from sparkflow_tpu.utils.metrics import Metrics

    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=64,
                               num_layers=2, num_heads=4, mlp_dim=128,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    num_slots = 8
    metrics = Metrics()
    eng = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                       seed=0, metrics=metrics)

    # mixed-length workload: mostly-short with a long tail — the shape
    # continuous batching exists for (a 24-token completion next to 3s)
    budgets = [3, 4, 3, 3, 3, 4, 3, 24] * 4
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 5))]
               for _ in budgets]
    useful = sum(budgets)

    def run_static():
        done_tokens = 0
        t0 = time.perf_counter()
        for g in range(0, len(budgets), num_slots):
            group = list(range(g, min(g + num_slots, len(budgets))))
            # static batching's other cost: every member reserves KV for
            # the group's LONGEST budget, since it stays resident (and
            # keeps being stepped) until the whole group finishes
            group_max = max(budgets[i] for i in group)
            slots = {}
            for i in group:
                info = eng.prefill(prompts[i], max_new_tokens=group_max,
                                   temperature=0.0)
                slots[info["slot"]] = [i, 1]  # request, tokens so far
            # the whole group steps until its longest member is done
            for _ in range(group_max - 1):
                out = eng.step()
                for slot, (i, n) in slots.items():
                    if slot in out and n < budgets[i]:
                        slots[slot][1] = min(budgets[i], n + len(out[slot]))
            for slot, (i, n) in slots.items():
                done_tokens += n
                eng.release(slot)
        return done_tokens, time.perf_counter() - t0

    def run_continuous():
        cb = ContinuousBatcher(eng, max_queue=len(budgets) + 1,
                               metrics=metrics)
        t0 = time.perf_counter()
        futs = [cb.submit(p, max_new_tokens=b, temperature=0.0)
                for p, b in zip(prompts, budgets)]
        done_tokens = sum(f.result(timeout=600)["num_tokens"] for f in futs)
        dt = time.perf_counter() - t0
        cb.close()
        return done_tokens, dt

    # warm both paths once (first step after prefill pays dispatch setup)
    info = eng.prefill(prompts[0][:2], max_new_tokens=2, temperature=0.0)
    eng.step()
    eng.release(info["slot"])

    static_tokens, static_s = run_static()
    cont_tokens, cont_s = run_continuous()
    assert static_tokens == cont_tokens == useful, \
        (static_tokens, cont_tokens, useful)

    static_tps = useful / static_s
    cont_tps = useful / cont_s
    speedup = cont_tps / static_tps
    pct = metrics.percentiles("serving/decode/token_latency_ms", (50, 99))
    p50, p99 = pct["p50"], pct["p99"]
    out = {
        "metric": "decode_continuous_vs_static_speedup",
        "value": round(speedup, 2),
        "unit": "x tokens/sec",
        "threshold": 2.0,
        "pass": speedup >= 2.0,
        "continuous_tokens_per_sec": round(cont_tps, 1),
        "static_tokens_per_sec": round(static_tps, 1),
        "token_latency_p50_ms": round(p50, 2),
        "token_latency_p99_ms": round(p99, 2),
        "requests": len(budgets),
        "useful_tokens": useful,
        "num_slots": num_slots,
        "steady_traces": eng.stats()["steady_traces"],
    }
    print(json.dumps(out))


def prefix_cache_main():
    """Shared-prefix KV caching + chunked prefill for the decode plane.
    Prints THREE JSON lines, one per pinned claim:

    - ``decode_prefix_hit_ttft_speedup`` — time-to-first-token on a
      prefix-hit prompt (shared system prefix already indexed) vs a cold
      prompt of the same length. The hit prefills only the un-shared
      suffix, so the ladder pass over the shared 40 tokens disappears.
    - ``decode_shared_prefix_throughput_gain`` — tokens/sec of a
      shared-system-prompt workload (16 requests, same 40-token prefix)
      through the ContinuousBatcher with sharing on vs off.
    - ``decode_chunked_prefill_intertoken_p95`` — inter-token p95 of
      in-flight short decodes while a 48-token prompt arrives mid-stream:
      unchunked (monolithic prefill stalls the decode loop) over chunked
      (prefill fused into the decode step, one chunk per step). >1 means
      chunking lowered the stall.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import functools

    import jax

    from sparkflow_tpu import ops
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import decode as decode_mod
    from sparkflow_tpu.serving.batcher import ContinuousBatcher
    from sparkflow_tpu.serving.decode import DecodeEngine

    # On CPU the pallas decode kernel runs in interpret mode (~100ms/step
    # for this model — pure emulation overhead that buries the prefill-side
    # effects this bench pins). interpret=False makes paged_attention fall
    # back to its compiled jnp reference on CPU: same math, cheap steps, the
    # TPU-like regime where prefill compute is the cost that matters. Both
    # arms of every comparison run the identical kernel, so ratios are fair.
    decode_mod.paged_attention = functools.partial(ops.paged_attention,
                                                   interpret=False)

    # big enough that prefill compute dominates per-call dispatch overhead
    # on CPU — with a toy model every device call costs the same ~1.5ms and
    # no prefill optimization can show up in wall time
    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=256,
                               num_layers=4, num_heads=4, mlp_dim=1024,
                               max_len=128, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    sys_prefix = [int(t) for t in rs.randint(1, 97, size=96)]

    eng = DecodeEngine(model, params, num_slots=8, page_size=8, seed=0)

    # -- (a) TTFT: prefix hit vs cold ------------------------------------
    def ttft(prompt):
        t0 = time.perf_counter()
        info = eng.prefill(prompt, max_new_tokens=2, temperature=0.0)
        dt = time.perf_counter() - t0
        eng.release(info["slot"])
        return dt

    ttft(sys_prefix + [1, 2, 3, 4, 5, 6, 7, 8])  # seed index, warm dispatch
    repeats = 8
    hit_s = sorted(ttft(sys_prefix
                        + [int(t) for t in rs.randint(1, 97, size=8)])
                   for _ in range(repeats))[repeats // 2]
    cold_s = sorted(ttft([int(t) for t in rs.randint(1, 97, size=104)])
                    for _ in range(repeats))[repeats // 2]
    ttft_speedup = cold_s / hit_s
    hits_after_a = eng.kv.stats()["prefix_hits"]
    print(json.dumps({
        "metric": "decode_prefix_hit_ttft_speedup",
        "value": round(ttft_speedup, 2),
        "unit": "x cold/hit median TTFT",
        "threshold": 2.0,
        "pass": ttft_speedup >= 2.0,
        "ttft_hit_ms": round(hit_s * 1e3, 2),
        "ttft_cold_ms": round(cold_s * 1e3, 2),
        "prompt_len": 104,
        "shared_tokens": 96,
        "repeats": repeats,
        "prefix_hits": hits_after_a,
    }))

    # -- (b) shared-system-prompt workload throughput, sharing on vs off -
    tails = [[int(a), int(b)] for a, b in rs.randint(1, 97, size=(16, 2))]

    def workload_tps(engine):
        cb = ContinuousBatcher(engine, max_queue=32)
        try:
            t0 = time.perf_counter()
            futs = [cb.submit(sys_prefix + tail, max_new_tokens=8,
                              temperature=0.0) for tail in tails]
            toks = sum(f.result(timeout=600)["num_tokens"] for f in futs)
            return toks / (time.perf_counter() - t0)
        finally:
            cb.close()

    eng_off = DecodeEngine(model, params, num_slots=8, page_size=8, seed=0,
                           prefix_cache=False)
    workload_tps(eng_off)          # warm the off engine's dispatch path
    tps_off = workload_tps(eng_off)
    tps_on = workload_tps(eng)     # eng is warm from (a)
    tps_gain = tps_on / tps_off
    print(json.dumps({
        "metric": "decode_shared_prefix_throughput_gain",
        "value": round(tps_gain, 2),
        "unit": "x tokens/sec, sharing on/off",
        "threshold": 1.2,
        "pass": tps_gain >= 1.2,
        "tokens_per_sec_shared": round(tps_on, 1),
        "tokens_per_sec_unshared": round(tps_off, 1),
        "requests": len(tails),
        "tokens_saved": eng.kv.stats()["tokens_saved"],
        "steady_traces": eng.stats()["steady_traces"],
    }))

    # -- (c) inter-token p95 with a long prompt arriving mid-stream ------
    # a FRESH random long prompt per run: a reused one would be committed
    # to the prefix index by the first run, and the replay would prefill
    # only an 8-token suffix — erasing the very stall being measured
    def fresh_long():
        return [int(t) for t in rs.randint(1, 97, size=96)]

    def intertoken_gaps(engine, long_prompt):
        shorts = [engine.prefill([9 + i, 3 + i], max_new_tokens=12,
                                 temperature=0.0) for i in range(3)]
        last = {s["slot"]: time.perf_counter() for s in shorts}
        counts = {s["slot"]: 1 for s in shorts}
        gaps, long_slot = [], None
        for step_i in range(100):
            if step_i == 4:
                long_slot = engine.prefill(long_prompt, max_new_tokens=4,
                                           temperature=0.0)["slot"]
            out = engine.step()
            now = time.perf_counter()
            for s in list(counts):
                if s in out and counts[s] < 12:
                    gaps.append(now - last[s])
                    last[s] = now
                    counts[s] = min(12, counts[s] + len(out[s]))
                    if counts[s] == 12:
                        engine.release(s)
                        del counts[s], last[s]
            if not counts:
                break
        if long_slot is not None:
            engine.release(long_slot)
        return gaps

    eng_chunk = DecodeEngine(model, params, num_slots=8, page_size=8,
                             seed=0, prefill_chunk=8)
    intertoken_gaps(eng_chunk, fresh_long())   # warm both paths once
    intertoken_gaps(eng, fresh_long())
    p95 = lambda xs: float(np.percentile(np.asarray(xs) * 1e3, 95))
    p95_chunk = p95(intertoken_gaps(eng_chunk, fresh_long()))
    p95_mono = p95(intertoken_gaps(eng, fresh_long()))
    stall_ratio = p95_mono / p95_chunk
    print(json.dumps({
        "metric": "decode_chunked_prefill_intertoken_p95",
        "value": round(stall_ratio, 2),
        "unit": "x unchunked/chunked p95 gap",
        "threshold": 1.2,
        "pass": stall_ratio >= 1.2,
        "p95_unchunked_ms": round(p95_mono, 2),
        "p95_chunked_ms": round(p95_chunk, 2),
        "long_prompt_len": 96,
        "prefill_chunk": 8,
        "steady_traces_chunked": eng_chunk.stats()["steady_traces"],
    }))


def hot_swap_main():
    """Live weight hot-swap under sustained decode load: the same
    continuous-batching burst with and without a mid-burst publish + watcher
    swap. Prints ONE JSON line:
    {"metric": "decode_hot_swap_intertoken_p95", ...}.

    The swap arm runs a real WeightStore + WeightWatcher: one third of the
    way into the burst a new version is published; the watcher pulls,
    verifies, and hands it to the engine, which holds admissions until the
    active slots drain and then swaps at the token boundary. The pinned
    claims: zero client-visible failures, the serving version flips exactly
    ONCE, inter-token p95 stays within 1.3x the no-swap arm (in-flight
    sequences keep stepping through the drain — only admission waits), zero
    steady-state retraces (the AOT decode step is reused as-is), and the
    post-swap params are bitwise the published tree (greedy output equals a
    cold start on the new weights).
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import jax

    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving.batcher import ContinuousBatcher
    from sparkflow_tpu.serving.decode import DecodeEngine
    from sparkflow_tpu.serving.weightstore import WeightStore, WeightWatcher
    from sparkflow_tpu.utils.metrics import Metrics

    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=64,
                               num_layers=2, num_heads=4, mlp_dim=128,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    p_old = model.init(jax.random.PRNGKey(0))
    p_new = model.init(jax.random.PRNGKey(1))

    budgets = [4, 3, 5, 3, 4, 3, 6, 3] * 6
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 5))]
               for _ in budgets]
    useful = sum(budgets)

    def run(with_swap):
        metrics = Metrics()
        eng = DecodeEngine(model, p_old, num_slots=8, page_size=8, seed=0,
                           metrics=metrics)
        info = eng.prefill(prompts[0][:2], max_new_tokens=2, temperature=0.0)
        eng.step()
        eng.release(info["slot"])  # warm: first step pays dispatch setup
        store = watcher = None
        if with_swap:
            store = WeightStore(tempfile.mkdtemp(prefix="hotswap_bench_"))
            watcher = WeightWatcher(store, [eng],
                                    poll_interval_s=0.005).start()
        cb = ContinuousBatcher(eng, max_queue=len(budgets) + 1,
                               metrics=metrics)
        failures = 0
        t0 = time.perf_counter()
        futs = [cb.submit(p, max_new_tokens=b, temperature=0.0)
                for p, b in zip(prompts, budgets)]
        if with_swap:
            while sum(f.done() for f in futs) < len(futs) // 3:
                time.sleep(0.002)
            store.publish(p_new)  # mid-burst: the watcher takes it from here
        tokens = 0
        for f in futs:
            try:
                tokens += f.result(timeout=600)["num_tokens"]
            except Exception:
                failures += 1
        dt = time.perf_counter() - t0
        cb.close()
        if with_swap:
            deadline = time.perf_counter() + 10.0
            while (eng.serving_version() != 1
                   and time.perf_counter() < deadline):
                eng.maybe_swap()  # drained after the burst: lands now
                time.sleep(0.01)
            watcher.stop()
        p95 = metrics.percentiles("serving/decode/token_latency_ms",
                                  (95,))["p95"]
        return eng, tokens, dt, p95, failures

    eng_base, tok_base, s_base, p95_base, fail_base = run(False)
    eng_swap, tok_swap, s_swap, p95_swap, fail_swap = run(True)

    assert tok_base == tok_swap == useful, (tok_base, tok_swap, useful)
    swap_stats = eng_swap.stats()
    # bitwise: the swapped engine IS a cold start on the published tree
    cold = DecodeEngine(model, p_new, num_slots=8, page_size=8, seed=0)
    leaves_a = jax.tree.leaves(eng_swap._params)
    leaves_b = jax.tree.leaves(cold._params)
    bitwise = len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves_a, leaves_b))

    def greedy(e, prompt, n):
        info = e.prefill(list(prompt), max_new_tokens=n, temperature=0.0)
        toks = [info["token"]]
        while len(toks) < n:
            toks.extend(e.step().get(info["slot"], []))
        e.release(info["slot"])
        return toks

    parity = greedy(eng_swap, prompts[0], 6) == greedy(cold, prompts[0], 6)
    ratio = p95_swap / max(p95_base, 1e-9)
    out = {
        "metric": "decode_hot_swap_intertoken_p95",
        "value": round(ratio, 2),
        "unit": "x swap/no-swap p95",
        "threshold": 1.3,
        "pass": (ratio <= 1.3 and fail_base == fail_swap == 0
                 and swap_stats["swaps"] == 1 and bitwise and parity
                 and swap_stats["steady_traces"] == 0),
        "p95_no_swap_ms": round(p95_base, 2),
        "p95_swap_ms": round(p95_swap, 2),
        "tokens_per_sec_no_swap": round(tok_base / s_base, 1),
        "tokens_per_sec_swap": round(tok_swap / s_swap, 1),
        "client_failures": fail_base + fail_swap,
        "version_flips": swap_stats["swaps"],
        "serving_version": swap_stats["serving_version"],
        "bitwise_params_parity": bitwise,
        "greedy_parity": parity,
        "steady_traces": swap_stats["steady_traces"],
        "requests": len(budgets),
        "useful_tokens": useful,
    }
    print(json.dumps(out))


def spec_decode_main():
    """Speculative decoding on the paged decode plane: spec-on vs spec-off
    tokens/sec and inter-token p95. Prints ONE JSON line:
    {"metric": "decode_spec_speedup", ...}.

    Honest accounting: both arms monkeypatch the paged decode AND verify
    kernels to their compiled jnp references (interpret=False falls back on
    CPU — same math, no pallas-interpreter emulation tax), so the ratio
    isolates what speculation actually changes: device dispatches per token.
    The draft is acceptance-favorable self-speculation with ``draft_layers
    == num_layers`` (the draft IS the target, so every greedy proposal is
    accepted) — the CPU-measurable win is dispatch amortization, k+1 tokens
    per draft+verify pair instead of one per step; the TPU win adds the
    FLOP gap between a real truncated draft and the full target. Greedy
    parity between the arms is asserted, not assumed.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import functools

    import jax

    from sparkflow_tpu import ops
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import decode as decode_mod
    from sparkflow_tpu.serving.decode import DecodeEngine
    from sparkflow_tpu.utils.metrics import Metrics

    decode_mod.paged_attention = functools.partial(ops.paged_attention,
                                                   interpret=False)
    decode_mod.paged_attention_verify = functools.partial(
        ops.paged_attention_verify, interpret=False)

    # small model: per-call dispatch dominates compute, which is the regime
    # speculation's fewer-dispatches-per-token targets (on CPU; a TPU run
    # would also show the draft/target FLOP gap)
    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    num_slots, budget, spec_k = 8, 48, 11
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 6))]
               for _ in range(num_slots)]

    def run_arm(engine, budget):
        infos = [engine.prefill(p, max_new_tokens=budget, temperature=0.0)
                 for p in prompts]
        got = {i["slot"]: [i["token"]] for i in infos}
        live = set(got)
        t0 = time.perf_counter()
        while live:
            out = engine.step()
            for s in list(live):
                if s in out:
                    got[s].extend(out[s])
                    if len(got[s]) >= budget:
                        engine.release(s)
                        live.discard(s)
        dt = time.perf_counter() - t0
        order = [i["slot"] for i in infos]
        return [got[s][:budget] for s in order], dt

    def build(spec_on):
        m = Metrics()
        kw = dict(spec_k=spec_k, draft_layers=2) if spec_on else {}
        eng = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                           seed=0, metrics=m, **kw)
        run_arm(eng, 4)                 # warm the dispatch path
        return eng, m

    eng_off, m_off = build(False)
    eng_on, m_on = build(True)
    # interleaved paired reps: each rep times both arms back to back so
    # they share the machine's conditions of the moment, and the claimed
    # speedup is the MEDIAN of per-rep ratios — a single noisy rep (GC
    # pause, scheduler hiccup; the measured sections are only tens of ms)
    # can't flap the gate either way
    reps = 10
    ratios, dt_off_best, dt_on_best = [], None, None
    toks_off = toks_on = None
    for _ in range(reps):
        t_off, d_off = run_arm(eng_off, budget)
        t_on, d_on = run_arm(eng_on, budget)
        if toks_off is None:
            toks_off, toks_on = t_off, t_on
        assert t_off == toks_off and t_on == toks_on, \
            "greedy output unstable across reps"
        ratios.append(d_off / d_on)
        dt_off_best = d_off if dt_off_best is None else min(dt_off_best, d_off)
        dt_on_best = d_on if dt_on_best is None else min(dt_on_best, d_on)
    assert toks_on == toks_off, "speculative greedy output diverged"
    tps_off = num_slots * budget / dt_off_best
    tps_on = num_slots * budget / dt_on_best
    st_on = eng_on.stats()
    p95_off = m_off.percentiles("serving/decode/token_latency_ms",
                                (95,))["p95"]
    p95_on = m_on.percentiles("serving/decode/token_latency_ms",
                              (95,))["p95"]
    speedup = sorted(ratios)[len(ratios) // 2]
    p95_ratio = p95_off / p95_on
    print(json.dumps({
        "metric": "decode_spec_speedup",
        "value": round(speedup, 2),
        "unit": "x tokens/sec, spec on/off",
        "threshold": 1.5,
        "pass": bool(speedup >= 1.5 and p95_ratio > 1.0),
        "tokens_per_sec_spec": round(tps_on, 1),
        "tokens_per_sec_plain": round(tps_off, 1),
        "intertoken_p95_spec_ms": round(p95_on, 2),
        "intertoken_p95_plain_ms": round(p95_off, 2),
        "intertoken_p95_ratio": round(p95_ratio, 2),
        "spec_k": spec_k,
        "accept_rate": round(st_on["spec"]["accept_rate"], 3),
        "mean_accepted": round(st_on["spec"]["mean_accepted"], 2),
        "greedy_parity": True,
        "steady_traces_spec": st_on["steady_traces"],
    }))


def kv_quant_main():
    """Quantized KV cache: int8 pool vs bf16/f32 pool on the paged decode
    plane. Prints ONE JSON line: {"metric": "decode_kv_quant", ...}.

    Three claims, one run:

    - capacity: pages-per-byte from the engines' own ``stats()`` byte
      accounting — the int8 pool (rows + per-page-per-head scales) must fit
      >= 1.9x the pages into the same device bytes;
    - parity: tokens/sec int8 vs float on the same workload, MEDIAN of
      interleaved per-rep ratios, with greedy output asserted
      token-identical between the arms (quantization error ~1e-4 logits on
      this model, far under any argmax margin);
    - overload: byte-equalized pools (the int8 arm spends its byte budget
      on ~4x the pages) driven through the ContinuousBatcher at 2x the
      float arm's concurrent capacity — the admission-rejection rate read
      off ``batcher.stats()`` must DROP on the quantized arm.

    Honest accounting: both arms trace under ``force_xla_attention()`` so
    every AOT program runs the interpret=False reference kernels (same
    math, no pallas-interpreter emulation tax on CPU); the ratio isolates
    what the pool layout changes — dequant arithmetic and page bytes.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from concurrent.futures import wait

    import jax

    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.ops.attention import force_xla_attention
    from sparkflow_tpu.serving import ContinuousBatcher, DecodeEngine, \
        QueueFull
    from sparkflow_tpu.utils.metrics import Metrics

    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    num_slots, budget = 8, 24
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 6))]
               for _ in range(num_slots)]

    def build(kv_quant, num_pages=None):
        with force_xla_attention():
            return DecodeEngine(model, params, num_slots=num_slots,
                                page_size=8, num_pages=num_pages, seed=0,
                                kv_quant=kv_quant, metrics=Metrics())

    def run_arm(engine, budget):
        infos = [engine.prefill(p, max_new_tokens=budget, temperature=0.0)
                 for p in prompts]
        got = {i["slot"]: [i["token"]] for i in infos}
        live = set(got)
        t0 = time.perf_counter()
        while live:
            out = engine.step()
            for s in list(live):
                if s in out:
                    got[s].extend(out[s])
                    if len(got[s]) >= budget:
                        engine.release(s)
                        live.discard(s)
        dt = time.perf_counter() - t0
        return [got[i["slot"]][:budget] for i in infos], dt

    eng_ref = build("bf16")
    eng_q = build("int8")
    run_arm(eng_ref, 4)                   # warm the dispatch paths
    run_arm(eng_q, 4)

    # -- capacity: pages per byte straight off the stats() accounting
    bpp_ref = eng_ref.stats()["kv"]["kv_bytes_per_page"]
    bpp_q = eng_q.stats()["kv"]["kv_bytes_per_page"]
    pages_per_byte_ratio = bpp_ref / bpp_q

    # -- parity: interleaved paired reps, median of per-rep ratios (one
    # noisy rep can't flap the gate), greedy text must not move at all
    reps = 7
    ratios, toks_ref, toks_q = [], None, None
    for _ in range(reps):
        t_ref, d_ref = run_arm(eng_ref, budget)
        t_q, d_q = run_arm(eng_q, budget)
        if toks_ref is None:
            toks_ref, toks_q = t_ref, t_q
        assert t_ref == toks_ref and t_q == toks_q, \
            "greedy output unstable across reps"
        ratios.append(d_ref / d_q)
    parity = toks_q == toks_ref
    tps_ratio = sorted(ratios)[len(ratios) // 2]

    # -- overload: same device byte budget, 2x the float arm's concurrent
    # capacity offered to both batchers. Each request needs 4 pages
    # (4-token prompt + 28 new = 32 tokens); the float pool holds 3
    # concurrent, the int8 pool turns the same bytes into enough pages
    # that all 8 slots admit.
    pages_ref = 13                            # 12 usable + scratch
    byte_budget = (pages_ref - 1) * bpp_ref
    pages_q = 1 + int(byte_budget // bpp_q)
    ov_ref = build("bf16", num_pages=pages_ref)
    ov_q = build("int8", num_pages=pages_q)
    prompt, new_toks = [5, 2, 8, 3], 28       # 32 tokens = 4 pages/request
    cap_ref = (pages_ref - 1) // 4
    target = 2 * cap_ref                      # 2x the float arm's capacity

    def overload(engine):
        """Closed loop: keep ``target`` generations outstanding for a fixed
        window, topping up the moment one completes; every top-up the
        batcher refuses at the door (queue of 1 already full because the
        pool can't admit) counts against this pool layout."""
        bat = ContinuousBatcher(engine, max_queue=1)
        futs = []
        try:
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                futs = [f for f in futs if not f.done()]
                while len(futs) < target:
                    try:
                        futs.append(bat.submit(prompt,
                                               max_new_tokens=new_toks))
                    except QueueFull:
                        break                 # counted by the batcher
                time.sleep(0.005)
            wait(futs, timeout=120)
            st = bat.stats()
        finally:
            bat.close()
        return st

    st_ref = overload(ov_ref)
    st_q = overload(ov_q)
    rej_ref = st_ref["rejection_rate"]
    rej_q = st_q["rejection_rate"]

    ok = bool(pages_per_byte_ratio >= 1.9 and parity
              and tps_ratio >= 0.7 and rej_q < rej_ref)
    print(json.dumps({
        "metric": "decode_kv_quant",
        "value": round(pages_per_byte_ratio, 2),
        "unit": "x pages per device byte, int8 vs float pool",
        "threshold": 1.9,
        "pass": ok,
        "bytes_per_page_float": bpp_ref,
        "bytes_per_page_int8": bpp_q,
        "tokens_per_sec_ratio_int8_vs_float": round(tps_ratio, 2),
        "greedy_parity": parity,
        "kv_quant_error": eng_q.stats()["kv_quant_error"],
        "overload_pages_float": pages_ref - 1,
        "overload_pages_int8": pages_q - 1,
        "overload_offered": st_ref["submitted"],
        "overload_capacity_float": cap_ref,
        "rejection_rate_float": round(rej_ref, 3),
        "rejection_rate_int8": round(rej_q, 3),
        "steady_traces_int8": eng_q.stats()["steady_traces"],
        "platform": "cpu",
    }))


def tp_decode_main():
    """Tensor-parallel decode: tp=2 over a 2-virtual-device CPU mesh vs the
    same engine unsharded. Prints ONE JSON line:
    {"metric": "decode_tp_shard", ...}.

    What a CPU host can honestly measure about TP is **placement and
    parity**, not speed — two host-backed virtual devices share the same
    cores, so the gate is (a) greedy token parity tp=2 vs tp=1 through the
    REAL interpret-mode pallas kernels (each shard running the unmodified
    kernel over its heads slice), and (b) the structural claim: at-rest
    KV+param bytes per device at ~1/tp of the replicated baseline, read
    from ``stats()['parallel']``. Throughput/p95 for both arms are measured
    anyway — interleaved paired reps, median of per-rep ratios, exactly the
    spec-decode protocol — and reported informationally (expect ~1x or
    worse on CPU; the TPU win is the halved per-device weight/KV residency
    and the matmul split across chips).
    """
    _zero_bench_env(2)
    import functools

    import jax

    from sparkflow_tpu import ops
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.parallel.mesh import make_mesh
    from sparkflow_tpu.serving import decode as decode_mod
    from sparkflow_tpu.serving.decode import DecodeEngine
    from sparkflow_tpu.sharding import ShardingConfig
    from sparkflow_tpu.utils.metrics import Metrics

    spec = build_registry_spec("transformer_lm", vocab_size=97, hidden=64,
                               num_layers=2, num_heads=4, mlp_dim=128,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    mesh = make_mesh({"tp": 2})
    cfg = ShardingConfig(tp_axis="tp")
    num_slots, budget = 8, 32
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 6))]
               for _ in range(num_slots)]

    def run_arm(engine, budget):
        infos = [engine.prefill(p, max_new_tokens=budget, temperature=0.0)
                 for p in prompts]
        got = {i["slot"]: [i["token"]] for i in infos}
        live = set(got)
        t0 = time.perf_counter()
        while live:
            out = engine.step()
            for s in list(live):
                if s in out:
                    got[s].extend(out[s])
                    if len(got[s]) >= budget:
                        engine.release(s)
                        live.discard(s)
        dt = time.perf_counter() - t0
        order = [i["slot"] for i in infos]
        return [got[s][:budget] for s in order], dt

    # parity arm: the real pallas kernels (interpret mode on CPU), shards
    # feeding the unmodified kernel their local heads slice
    par1 = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                        seed=0)
    par2 = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                        seed=0, mesh=mesh, sharding=cfg)
    pt1, _ = run_arm(par1, 8)
    pt2, _ = run_arm(par2, 8)
    kernel_parity = pt1 == pt2
    assert kernel_parity, "tp=2 diverged from tp=1 under the pallas kernels"

    # timing arms: compiled jnp reference kernels (interpret=False falls
    # back on CPU) so the ratio reflects orchestration, not interpreter tax
    decode_mod.paged_attention = functools.partial(ops.paged_attention,
                                                   interpret=False)
    decode_mod.paged_attention_verify = functools.partial(
        ops.paged_attention_verify, interpret=False)
    m1, m2 = Metrics(), Metrics()
    eng1 = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                        seed=0, metrics=m1)
    eng2 = DecodeEngine(model, params, num_slots=num_slots, page_size=8,
                        seed=0, metrics=m2, mesh=mesh, sharding=cfg)
    run_arm(eng1, 4)  # warm the dispatch paths
    run_arm(eng2, 4)
    reps = 10
    ratios, toks1, toks2 = [], None, None
    dt1_best = dt2_best = None
    for _ in range(reps):
        t1, d1 = run_arm(eng1, budget)
        t2, d2 = run_arm(eng2, budget)
        if toks1 is None:
            toks1, toks2 = t1, t2
        assert t1 == toks1 and t2 == toks2, \
            "greedy output unstable across reps"
        ratios.append(d1 / d2)
        dt1_best = d1 if dt1_best is None else min(dt1_best, d1)
        dt2_best = d2 if dt2_best is None else min(dt2_best, d2)
    assert toks1 == toks2, "tp=2 greedy output diverged from tp=1"
    s1, s2 = eng1.stats(), eng2.stats()
    b1 = (s1["parallel"]["kv_bytes_per_device"]
          + s1["parallel"]["param_bytes_per_device"])
    b2 = (s2["parallel"]["kv_bytes_per_device"]
          + s2["parallel"]["param_bytes_per_device"])
    mem_ratio = b2 / b1
    speed = sorted(ratios)[len(ratios) // 2]
    p95_1 = m1.percentiles("serving/decode/token_latency_ms", (95,))["p95"]
    p95_2 = m2.percentiles("serving/decode/token_latency_ms", (95,))["p95"]
    ok = kernel_parity and mem_ratio <= 0.65 \
        and s2["steady_traces"] == 0
    print(json.dumps({
        "metric": "decode_tp_shard",
        "value": round(mem_ratio, 3),
        "unit": "per-device KV+param bytes, tp=2 / tp=1",
        "threshold": 0.65,
        "pass": bool(ok),
        "kv_bytes_per_device_tp1": s1["parallel"]["kv_bytes_per_device"],
        "kv_bytes_per_device_tp2": s2["parallel"]["kv_bytes_per_device"],
        "param_bytes_per_device_tp1": s1["parallel"]["param_bytes_per_device"],
        "param_bytes_per_device_tp2": s2["parallel"]["param_bytes_per_device"],
        "tp_speed_ratio_median": round(speed, 2),
        "tokens_per_sec_tp1": round(num_slots * budget / dt1_best, 1),
        "tokens_per_sec_tp2": round(num_slots * budget / dt2_best, 1),
        "intertoken_p95_tp1_ms": round(p95_1, 2),
        "intertoken_p95_tp2_ms": round(p95_2, 2),
        "greedy_parity": True,
        "kernel_parity": bool(kernel_parity),
        "steady_traces_tp2": s2["steady_traces"],
        "tp": 2,
        "platform": "cpu-hostdevices",
    }))


def pp_decode_main():
    """Pipeline-parallel decode: pp=2 over a 2-virtual-device CPU mesh.
    Prints ONE JSON line: {"metric": "decode_pp_wave", ...}.

    Two claims, two gates. (a) Structural: at-rest KV+param bytes per
    device at ~1/pp of the replicated baseline (the pool shards on its
    layers axis, the params stage-stack), plus greedy token parity pp=2
    vs pp=1 through the REAL interpret-mode pallas kernels under BOTH
    schedules. (b) Scheduling: micro-token wave scheduling vs the
    single-wave pp schedule at equal batch, tokens/sec median-of-ratios
    >= 1.5x. Unlike the tp bench this speed gate is honest on CPU: the
    single-wave schedule burns pp passes of every-stage compute per
    token (1/pp efficiency by construction), while waves keep every
    stage usefully busy on a different wave's token — the ratio measures
    bubble amortization, not device count. Timing arms run the
    compiled jnp reference kernels (interpret=False falls back on CPU)
    on a compute-bound model so orchestration, not interpreter tax,
    sets the clock; interleaved paired reps, spec-decode protocol.
    """
    _zero_bench_env(2)
    import functools

    import jax

    from sparkflow_tpu import ops
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.parallel.mesh import make_mesh
    from sparkflow_tpu.serving import decode as decode_mod
    from sparkflow_tpu.serving.decode import DecodeEngine
    from sparkflow_tpu.sharding import ShardingConfig
    from sparkflow_tpu.utils.metrics import Metrics

    mesh = make_mesh({"pp": 2})
    cfg = ShardingConfig(pp_axis="pp")
    num_slots, budget = 16, 16
    rs = np.random.RandomState(0)

    def run_arm(engine, prompts, budget):
        infos = [engine.prefill(p, max_new_tokens=budget, temperature=0.0)
                 for p in prompts]
        got = {i["slot"]: [i["token"]] for i in infos}
        live = set(got)
        t0 = time.perf_counter()
        while live:
            out = engine.step()
            for s in list(live):
                if s in out:
                    got[s].extend(out[s])
                    if len(got[s]) >= budget:
                        engine.release(s)
                        live.discard(s)
        dt = time.perf_counter() - t0
        order = [i["slot"] for i in infos]
        return [got[s][:budget] for s in order], dt

    # parity arm: small model, the real pallas kernels (interpret mode on
    # CPU), both staged schedules against the unsharded engine
    pspec = build_registry_spec("transformer_lm", vocab_size=97, hidden=64,
                                num_layers=2, num_heads=4, mlp_dim=128,
                                max_len=64, dropout=0.0)
    pmodel = model_from_json(pspec)
    pparams = pmodel.init(jax.random.PRNGKey(0))
    pprompts = [[int(t) for t in rs.randint(1, 97, size=rs.randint(2, 6))]
                for _ in range(num_slots)]
    par1 = DecodeEngine(pmodel, pparams, num_slots=num_slots, page_size=8,
                        seed=0)
    parw = DecodeEngine(pmodel, pparams, num_slots=num_slots, page_size=8,
                        seed=0, mesh=mesh, sharding=cfg)
    pars = DecodeEngine(pmodel, pparams, num_slots=num_slots, page_size=8,
                        seed=0, mesh=mesh, sharding=cfg, pp_wave=False)
    pt1, _ = run_arm(par1, pprompts, 8)
    ptw, _ = run_arm(parw, pprompts, 8)
    pts, _ = run_arm(pars, pprompts, 8)
    kernel_parity = pt1 == ptw == pts
    assert kernel_parity, "pp=2 diverged from pp=1 under the pallas kernels"
    s1, sw = par1.stats(), parw.stats()
    b1 = (s1["parallel"]["kv_bytes_per_device"]
          + s1["parallel"]["param_bytes_per_device"])
    b2 = (sw["parallel"]["kv_bytes_per_device"]
          + sw["parallel"]["param_bytes_per_device"])
    mem_ratio = b2 / b1

    # timing arms: compute-bound model (blocks dominate the per-token
    # FLOPs; the head is schedule-neutral), reference kernels, BOTH arms
    # pp=2 — only the schedule differs
    # 16 heads keeps head_dim off the TPU tile sizes, so interpret=False
    # resolves to the compiled jnp reference kernel on CPU
    tspec = build_registry_spec("transformer_lm", vocab_size=512,
                                hidden=1024, num_layers=4, num_heads=16,
                                mlp_dim=4096, max_len=64, dropout=0.0)
    tmodel = model_from_json(tspec)
    tparams = tmodel.init(jax.random.PRNGKey(0))
    tprompts = [[int(t) for t in rs.randint(1, 512, size=rs.randint(2, 6))]
                for _ in range(num_slots)]
    decode_mod.paged_attention = functools.partial(ops.paged_attention,
                                                   interpret=False)
    decode_mod.paged_attention_verify = functools.partial(
        ops.paged_attention_verify, interpret=False)
    mw, ms = Metrics(), Metrics()
    eng_wave = DecodeEngine(tmodel, tparams, num_slots=num_slots,
                            page_size=8, seed=0, metrics=mw, mesh=mesh,
                            sharding=cfg)
    eng_sw = DecodeEngine(tmodel, tparams, num_slots=num_slots, page_size=8,
                          seed=0, metrics=ms, mesh=mesh, sharding=cfg,
                          pp_wave=False)
    run_arm(eng_wave, tprompts, 4)  # warm the dispatch paths
    run_arm(eng_sw, tprompts, 4)
    reps = 10
    ratios, toks_w, toks_s = [], None, None
    dtw_best = dts_best = None
    for _ in range(reps):
        ts, ds = run_arm(eng_sw, tprompts, budget)
        tw, dw = run_arm(eng_wave, tprompts, budget)
        if toks_w is None:
            toks_w, toks_s = tw, ts
        assert tw == toks_w and ts == toks_s, \
            "greedy output unstable across reps"
        ratios.append(ds / dw)
        dtw_best = dw if dtw_best is None else min(dtw_best, dw)
        dts_best = ds if dts_best is None else min(dts_best, ds)
    assert toks_w == toks_s, "wave scheduling diverged from single-wave"
    stw, sts = eng_wave.stats(), eng_sw.stats()
    speed = sorted(ratios)[len(ratios) // 2]
    p95_w = mw.percentiles("serving/decode/token_latency_ms", (95,))["p95"]
    p95_s = ms.percentiles("serving/decode/token_latency_ms", (95,))["p95"]
    ok = kernel_parity and mem_ratio <= 0.65 and speed >= 1.5 \
        and stw["steady_traces"] == 0 and sts["steady_traces"] == 0
    print(json.dumps({
        "metric": "decode_pp_wave",
        "value": round(speed, 2),
        "unit": "tokens/sec, wave / single-wave (both pp=2, equal batch)",
        "threshold": 1.5,
        "pass": bool(ok),
        "mem_ratio": round(mem_ratio, 3),
        "mem_threshold": 0.65,
        "kv_bytes_per_device_pp1": s1["parallel"]["kv_bytes_per_device"],
        "kv_bytes_per_device_pp2": sw["parallel"]["kv_bytes_per_device"],
        "param_bytes_per_device_pp1": s1["parallel"]["param_bytes_per_device"],
        "param_bytes_per_device_pp2": sw["parallel"]["param_bytes_per_device"],
        "tokens_per_sec_wave": round(num_slots * budget / dtw_best, 1),
        "tokens_per_sec_single_wave": round(num_slots * budget / dts_best, 1),
        "intertoken_p95_wave_ms": round(p95_w, 2),
        "intertoken_p95_single_wave_ms": round(p95_s, 2),
        "wave_ticks": stw["parallel"]["wave_ticks"],
        "greedy_parity": True,
        "kernel_parity": bool(kernel_parity),
        "steady_traces_wave": stw["steady_traces"],
        "steady_traces_single_wave": sts["steady_traces"],
        "pp": 2,
        "platform": "cpu-hostdevices",
    }))


def _zero_bench_env(n_dev: int = 8):
    """8 virtual CPU devices for the zero-stage benches: set BEFORE the
    first jax import (flags are read at backend init). Deterministic and
    hardware-independent — the memory numbers are structural (eval_shape
    byte accounting) and the step-time ratio compares two programs on the
    SAME backend."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_dev}")


def _zero_step_setup(stage: int, n_dev: int):
    """Build the jitted unified dp step for one zero stage plus its initial
    (params, opt_state), on an mlp big enough that step time is compute-
    not dispatch-bound on CPU."""
    import jax
    import jax.numpy as jnp
    from sparkflow_tpu.models import model_from_json
    from sparkflow_tpu.models.presets import mlp
    from sparkflow_tpu.optimizers import build_optimizer
    from sparkflow_tpu.optimizers_sharded import (
        place_zero1_state, shard_zero3_params, sharded_update,
        zero3_param_shardings)
    from sparkflow_tpu.parallel.dp import make_dp_train_step
    from sparkflow_tpu.parallel.mesh import make_mesh
    from sparkflow_tpu.sharding import ShardingConfig

    d_in, n_cls = 128, 10
    model = model_from_json(mlp(d_in, n_cls, hidden=(512, 512)))
    opt = build_optimizer("adam", 1e-3, None)
    mesh = make_mesh({"dp": n_dev})
    cfg = ShardingConfig(zero_stage=stage)
    step = make_dp_train_step(model, opt, mesh, "x:0", "y:0", sharding=cfg)
    p0 = model.init(jax.random.PRNGKey(0))
    if stage == 0:
        params, state = p0, opt.init(p0)
    else:
        state = place_zero1_state(
            sharded_update(opt, n_dev, "dp").init(p0), mesh, n_dev)
        if stage >= 3:
            params = shard_zero3_params(p0, n_dev)
            params = jax.tree.map(
                jax.device_put, params,
                zero3_param_shardings(params, mesh, n_dev))
        else:
            params = jax.tree.map(jnp.array, p0)
    return model, opt, mesh, step, params, state, p0


def _time_zero_step(step, params, state, n_dev, *, warmup=3, reps=20):
    """Median wall time of one compiled step (seconds)."""
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    batch = 8 * n_dev
    x = jnp.asarray(rs.randn(batch, 128), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)])
    mask = jnp.ones((batch,), jnp.float32)
    rng = jax.random.PRNGKey(1)
    times = []
    for i in range(warmup + reps):
        r = jax.random.fold_in(rng, i)
        t0 = time.perf_counter()
        params, state, loss = step(params, state, x, y, mask, r)
        jax.block_until_ready(loss)
        if i >= warmup:
            times.append(time.perf_counter() - t0)
    return float(np.median(times))


def dp_zero2_main():
    """ZeRO-2 vs ZeRO-1: same model, same mesh, both axes of the win.
    Prints ONE JSON line: {"metric": "dp_zero2_vs_zero1", ...}.

    - memory: grad+opt bytes live at update time (structural eval_shape
      accounting, ``optimizers_sharded.zero_memory_report``) vs the ideal
      1/dp floor — stage 2 must land within 1.3x of ideal (padding and the
      gathered-params buffer are the honest overhead).
    - time: median compiled step time, stage 2 / stage 1 — must stay
      within 1.10x (the all-gather moves updated params instead of
      updates; same bytes on the wire, so parity is the expectation).
    """
    _zero_bench_env(8)
    from sparkflow_tpu.optimizers_sharded import zero_memory_report

    n_dev = 8
    model, opt, mesh, step1, p1, s1, p0 = _zero_step_setup(1, n_dev)
    _, _, _, step2, p2, s2, _ = _zero_step_setup(2, n_dev)
    t1 = _time_zero_step(step1, p1, s1, n_dev)
    t2 = _time_zero_step(step2, p2, s2, n_dev)
    time_ratio = t2 / t1

    rep = zero_memory_report(opt, p0, n_dev, 2)
    bytes_ratio = rep["grad_opt_at_update"] / rep["ideal_grad_opt"]
    ok = bytes_ratio <= 1.3 and time_ratio <= 1.10
    out = {
        "metric": "dp_zero2_vs_zero1",
        "value": round(time_ratio, 3),
        "unit": "x step time vs zero1",
        "threshold": 1.10,
        "pass": bool(ok),
        "grad_opt_bytes_ratio_vs_ideal": round(bytes_ratio, 3),
        "bytes_threshold": 1.3,
        "grad_opt_at_update_bytes": rep["grad_opt_at_update"],
        "ideal_grad_opt_bytes": rep["ideal_grad_opt"],
        "zero1_step_ms": round(t1 * 1e3, 2),
        "zero2_step_ms": round(t2 * 1e3, 2),
        "dp": n_dev,
        "platform": "cpu-hostdevices",
    }
    print(json.dumps(out))


def dp_zero3_main():
    """ZeRO-3 at-rest memory: params + opt state per device vs replicated.
    Prints ONE JSON line: {"metric": "dp_zero3_memory", ...}.

    The value is the at-rest fraction (sharded bytes / replicated bytes);
    ideal is 1/dp, the threshold allows 1.3x of that for flat-layout
    padding. Step time vs zero1 is reported informationally — stage 3
    trades one all-gather per step for the 1/dp param residency.
    """
    _zero_bench_env(8)
    from sparkflow_tpu.optimizers_sharded import zero_memory_report

    n_dev = 8
    model, opt, mesh, step1, p1, s1, p0 = _zero_step_setup(1, n_dev)
    _, _, _, step3, p3, s3, _ = _zero_step_setup(3, n_dev)
    t1 = _time_zero_step(step1, p1, s1, n_dev)
    t3 = _time_zero_step(step3, p3, s3, n_dev)

    rep = zero_memory_report(opt, p0, n_dev, 3)
    at_rest = rep["params_at_rest"] + rep["opt_state_at_rest"]
    full = rep["full_params"] + rep["full_opt_state"]
    frac = at_rest / full
    threshold = 1.3 / n_dev
    out = {
        "metric": "dp_zero3_memory",
        "value": round(frac, 4),
        "unit": "at-rest bytes fraction vs replicated",
        "threshold": round(threshold, 4),
        "pass": bool(frac <= threshold),
        "params_at_rest_bytes": rep["params_at_rest"],
        "opt_state_at_rest_bytes": rep["opt_state_at_rest"],
        "full_params_bytes": rep["full_params"],
        "full_opt_state_bytes": rep["full_opt_state"],
        "zero1_step_ms": round(t1 * 1e3, 2),
        "zero3_step_ms": round(t3 * 1e3, 2),
        "zero3_vs_zero1_step_time": round(t3 / t1, 3),
        "dp": n_dev,
        "platform": "cpu-hostdevices",
    }
    print(json.dumps(out))


def sim_main():
    """Fleet-simulator bench: scale wall-clock pin, the legacy-vs-debit
    generate pick rule A/B in sim, and the REAL-fleet confirmation of the
    sim-found improvement. Prints ONE JSON line:
    {"metric": "sim_fleet_whatif", ...}.

    Three parts:

    1. **scale** — 1000 replicas x 1,000,000 requests through the full
       event loop (real policies, real breakers on the virtual clock);
       the wall-clock is the pinned claim ("fleet what-ifs are cheap").
    2. **sim A/B** — the heterogeneous-pool what-if that motivated the
       inflight-debited byte-headroom generate rule: legacy vs debit on
       the same trace, p95 ratio reported.
    3. **real confirm** — two real DecodeEngine replicas (one big KV
       pool, one small) behind a real RouterServer; concurrent generate
       bursts under each pick rule (module-swapped policy, everything
       else identical). The debit rule must not lose: the sim's
       prediction is only landed because this confirms it.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    import jax

    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import (ContinuousBatcher, DecodeEngine,
                                       InferenceServer, RouterServer,
                                       ServingClient, policies)
    from sparkflow_tpu.sim import (CostModel, FleetSimulator, ReplicaSpec,
                                   legacy_generate_pick_key,
                                   synthetic_trace)
    from sparkflow_tpu.sim.calibrate import StubEngine

    cost = CostModel.from_bench_notes()
    # -- part 1: scale pin ---------------------------------------------------
    wall_bound_s = 120.0
    tr = synthetic_trace(1_000_000, seed=7, rate_rps=40000.0,
                         prompt_range=(16, 1024), output_range=(8, 256))
    specs = [ReplicaSpec(slots=8, pages_total=4096) for _ in range(1000)]
    scale = FleetSimulator(specs, tr, cost, mode="generate", seed=0).run()
    scale_ok = (scale.completed + scale.rejected == 1_000_000
                and scale.wall_s <= wall_bound_s)

    # -- part 2: the sim A/B that found the rule -----------------------------
    specs = ([ReplicaSpec(slots=16, pages_total=8192,
                          kv_bytes_per_page=4 << 20) for _ in range(2)] +
             [ReplicaSpec(slots=16, pages_total=1024,
                          kv_bytes_per_page=1 << 20) for _ in range(6)])
    tr = synthetic_trace(20000, seed=3, rate_rps=900.0)
    legacy = FleetSimulator(specs, tr, cost, mode="generate", seed=0,
                            pick_key=legacy_generate_pick_key).run()
    debit = FleetSimulator(specs, tr, cost, mode="generate", seed=0).run()
    sim_ratio = legacy.latency_p95_ms / max(debit.latency_p95_ms, 1e-9)

    # -- part 3: real mixed-pool fleet confirm -------------------------------
    spec = build_registry_spec("transformer_lm", vocab_size=61, hidden=64,
                               num_layers=4, num_heads=4, mlp_dim=256,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))

    def burst_p95(pick_key_fn):
        engines = [DecodeEngine(model, params, num_slots=4, page_size=8,
                                num_pages=pages, seed=0)
                   for pages in (64, 9)]    # big pool vs tight pool
        cbs = [ContinuousBatcher(e, max_queue=32) for e in engines]
        servers = [InferenceServer(StubEngine(0.0), generate_batcher=cb,
                                   max_delay_ms=1.0).start() for cb in cbs]
        router = RouterServer([s.url for s in servers],
                              probe_interval_s=0.05,
                              dispatch_retries=3).start()
        orig = policies.generate_pick_key
        policies.generate_pick_key = pick_key_fn
        lats, errs = [], [0]
        try:
            m = router.membership
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if all(r.decode_pages_free > 0 for r in m.replicas):
                    break
                time.sleep(0.02)
            cli = ServingClient(router.url, timeout=60, retries=2)
            cli.generate([3, 1, 4], max_new_tokens=4)  # unmeasured warm-up
            lock = threading.Lock()

            # 3 prompt + 56 new tokens = 59 -> 8 pages @ page_size 8:
            # the tight pool (9 pages) holds ONE concurrent stream, the
            # big pool (64) is slot-limited at 4. A 10-wide burst is
            # where the rules diverge: legacy alternates on inflight
            # (near-even split -> the tight pool serializes its share
            # one generation at a time), the debit rule stops feeding
            # it once the debited headroom predicts exhaustion.
            def one(i):
                t0 = time.perf_counter()
                try:
                    cli.generate([1 + i % 50, 2, 3], max_new_tokens=56)
                    ok = True
                except Exception:  # noqa: BLE001 - counted
                    ok = False
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    if ok:
                        lats.append(dt)
                    else:
                        errs[0] += 1

            for wave in range(4):            # 4 bursts of 10 concurrent
                ths = [threading.Thread(target=one, args=(wave * 10 + i,))
                       for i in range(10)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join(timeout=60.0)
            cli.close()
        finally:
            policies.generate_pick_key = orig
            router.stop()
            for cb in cbs:
                cb.close()
            for s in servers:
                s.stop()
        lats.sort()
        p95 = lats[min(len(lats) - 1, int(round(0.95 * (len(lats) - 1))))] \
            if lats else float("inf")
        return p95, len(lats), errs[0]

    # debit arm: est matched to the workload (8 pages/stream), the
    # documented deployment knob — EST_PAGES_PER_STREAM defaults to the
    # production workload median, this harness decodes 59-token streams
    new_rule = policies.generate_pick_key
    debit_est8 = lambda v: new_rule(v, est_pages_per_stream=8)  # noqa: E731
    real_legacy_p95, n_legacy, e_legacy = burst_p95(legacy_generate_pick_key)
    real_debit_p95, n_debit, e_debit = burst_p95(debit_est8)
    real_ratio = real_legacy_p95 / max(real_debit_p95, 1e-9)
    # the confirmation: the sim-found rule must not lose on real hardware
    # (the structural effect measures ~1.2x; 1.05 absorbs burst noise)
    confirmed = (e_debit == 0 and n_debit == 40
                 and real_debit_p95 <= real_legacy_p95 * 1.05)

    out = {
        "metric": "sim_fleet_whatif",
        "scale_replicas": 1000,
        "scale_requests": 1_000_000,
        "scale_wall_s": round(scale.wall_s, 2),
        "scale_wall_bound_s": wall_bound_s,
        "scale_sim_time_s": round(scale.sim_time_s, 2),
        "scale_throughput_sim_rps": round(scale.completed
                                          / max(scale.wall_s, 1e-9)),
        "scale_digest": scale.digest[:16],
        "pass": bool(scale_ok),
        "sim_ab_legacy_p95_ms": round(legacy.latency_p95_ms, 1),
        "sim_ab_debit_p95_ms": round(debit.latency_p95_ms, 1),
        "sim_ab_p95_speedup": round(sim_ratio, 2),
        "sim_ab_legacy_queue_full": legacy.queue_full,
        "sim_ab_debit_queue_full": debit.queue_full,
        "real_legacy_p95_ms": round(real_legacy_p95, 1),
        "real_debit_p95_ms": round(real_debit_p95, 1),
        "real_p95_speedup": round(real_ratio, 2),
        "real_errors": e_legacy + e_debit,
        "real_confirmed": bool(confirmed),
        "platform": "cpu",
    }
    print(json.dumps(out))


def cold_start_main():
    """Zero-compile cold start bench: boot-to-first-token with vs without
    the serialized-executable store. Prints ONE JSON line:
    {"metric": "cold_start_boot", ...}.

    Three boots of the same engines (a predict MLP bucket ladder and a
    transformer DecodeEngine), same process, same machine:

    1. **populate** — boot with an empty ``ExecutableStore`` directory:
       full compiles, store saves every executable (untimed);
    2. **compile boot** — boot with NO store: every executable pays
       tracing + lowering + XLA (the status quo a spawned replica paid
       before this store existed);
    3. **serialized boot** — boot against the populated store: every
       executable deserializes (``coldstart/hits``), zero compiles.

    Boot time = constructor (which warms up the full AOT ladder) + the
    first real result (a predict / a prefill + one decode step). The
    pinned claim for BENCH_NOTES.md is the compile/serialized ratio; the
    elastic-fleet value is that this latency sits between "autoscaler
    ordered capacity" and "capacity takes traffic".
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import shutil
    import tempfile

    import jax
    import numpy as np

    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import DecodeEngine, InferenceEngine
    from sparkflow_tpu.utils.metrics import Metrics

    spec = build_registry_spec("transformer_lm", vocab_size=64, hidden=64,
                               num_layers=4, num_heads=4, mlp_dim=256,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))

    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph

    def mlp_graph():
        x = nn.placeholder([None, 8], name="x")
        h = nn.dense(x, 16, activation="relu")
        nn.mean_squared_error(x, nn.dense(h, 4, name="out"))

    rs = np.random.RandomState(0)
    weights = [rs.randn(8, 16).astype(np.float32),
               rs.randn(16).astype(np.float32),
               rs.randn(16, 4).astype(np.float32),
               rs.randn(4).astype(np.float32)]

    def boot_predict(exe_dir):
        t0 = time.perf_counter()
        eng = InferenceEngine(build_graph(mlp_graph), weights,
                              input_name="x:0",
                              output_name="out/BiasAdd:0", max_batch=8,
                              executable_dir=exe_dir)
        eng.predict(np.zeros((3, 8), np.float32))
        return time.perf_counter() - t0, eng

    def boot_decode(exe_dir):
        t0 = time.perf_counter()
        eng = DecodeEngine(model, params, num_slots=4, page_size=8,
                           num_pages=64, seed=0, metrics=Metrics(),
                           executable_dir=exe_dir)
        info = eng.prefill([5, 9, 2], max_new_tokens=2, temperature=0.0)
        eng.step()
        eng.release(info["slot"])
        return time.perf_counter() - t0, eng

    exe_dir = tempfile.mkdtemp(prefix="coldstart_bench_")
    try:
        boot_predict(exe_dir)          # populate (compile + save)
        boot_decode(exe_dir)
        p_cold_s, _ = boot_predict(None)         # full-compile boots
        d_cold_s, _ = boot_decode(None)
        p_warm_s, p_eng = boot_predict(exe_dir)  # serialized boots
        d_warm_s, d_eng = boot_decode(exe_dir)
        p_loads = p_eng.stats()["cold_start"]["serialized_loads"]
        d_loads = d_eng.stats()["cold_start"]["serialized_loads"]
    finally:
        shutil.rmtree(exe_dir, ignore_errors=True)

    # the claim: serialized boot is measurably below full-compile boot
    ok = (p_warm_s < p_cold_s and d_warm_s < d_cold_s
          and p_loads > 0 and d_loads > 0)
    out = {
        "metric": "cold_start_boot",
        "predict_compile_boot_s": round(p_cold_s, 4),
        "predict_serialized_boot_s": round(p_warm_s, 4),
        "predict_speedup": round(p_cold_s / max(p_warm_s, 1e-9), 2),
        "predict_serialized_loads": int(p_loads),
        "decode_compile_boot_s": round(d_cold_s, 4),
        "decode_serialized_boot_s": round(d_warm_s, 4),
        "decode_speedup": round(d_cold_s / max(d_warm_s, 1e-9), 2),
        "decode_serialized_loads": int(d_loads),
        "serialized_faster": bool(ok),
        "platform": "cpu",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    if "--span-overhead" in sys.argv:
        span_overhead_main()
    elif "--trace-overhead" in sys.argv:
        trace_overhead_main()
    elif "--decode-throughput" in sys.argv:
        decode_throughput_main()
    elif "--prefix-cache" in sys.argv:
        prefix_cache_main()
    elif "--spec-decode" in sys.argv:
        spec_decode_main()
    elif "--kv-quant" in sys.argv:
        kv_quant_main()
    elif "--hot-swap" in sys.argv:
        hot_swap_main()
    elif "--tp-decode" in sys.argv:
        tp_decode_main()
    elif "--pp-decode" in sys.argv:
        pp_decode_main()
    elif "--elastic-straggler" in sys.argv:
        elastic_straggler_main()
    elif "--dp-zero2" in sys.argv:
        dp_zero2_main()
    elif "--dp-zero3" in sys.argv:
        dp_zero3_main()
    elif "--sim" in sys.argv:
        sim_main()
    elif "--cold-start" in sys.argv:
        cold_start_main()
    else:
        main()
