"""Zero-rewrite migration: run a sparkflow TF1 model on sparkflow-tpu.

The reference serializes models as MetaGraphDef JSON
(``sparkflow/graph_utils.py:6-15``) and ships TF1 Saver checkpoints
(``sparkflow/tensorflow_model_loader.py``). Both work here UNCHANGED:

1. a TF1 ``build_graph`` JSON string trains via ``SparkAsyncDL`` directly
   (interpreted node-by-node in JAX — no TensorFlow at execution time);
2. a Saver checkpoint directory becomes a serving model via
   ``load_tensorflow_model`` with no graph rebuild (the checkpoint's own
   ``.meta`` is the serving graph).

Generating the TF1 artifacts below needs TensorFlow installed (it is only
used to CREATE the fixtures, mimicking a legacy sparkflow user's assets).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

SMOKE = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))


def make_legacy_artifacts(tmp="/tmp/sparkflow_tf1_demo"):
    """What an existing sparkflow user already has: a metagraph JSON and a
    trained TF1 Saver checkpoint."""
    import tensorflow as tf
    from google.protobuf import json_format
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()

    def dense(x, units, name, act=None):
        with tf1.variable_scope(name):
            k = tf1.get_variable("kernel", [int(x.shape[-1]), units],
                                 initializer=tf1.glorot_uniform_initializer())
            b = tf1.get_variable("bias", [units],
                                 initializer=tf1.zeros_initializer())
        y = tf1.nn.bias_add(tf1.matmul(x, k), b)
        return act(y) if act else y

    os.makedirs(tmp, exist_ok=True)
    g = tf1.Graph()
    with g.as_default():
        x = tf1.placeholder(tf.float32, [None, 2], name="x")
        y = tf1.placeholder(tf.float32, [None, 1], name="y")
        h = dense(x, 12, "d1", tf.nn.relu)
        out = tf1.sigmoid(dense(h, 1, "outer"), name="out_act")
        tf1.losses.log_loss(y, out)
        mg_json = json_format.MessageToJson(tf1.train.export_meta_graph())
        prefix = os.path.join(tmp, "to_load")
        with tf1.Session(graph=g) as sess:
            sess.run(tf1.global_variables_initializer())
            tf1.train.Saver().save(sess, prefix)
    return mg_json, prefix


if __name__ == "__main__":
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    from sparkflow_tpu.compat import USING_PYSPARK
    if USING_PYSPARK:
        from pyspark.sql import SparkSession
        from pyspark.ml.linalg import Vectors
    else:
        from sparkflow_tpu.localml import (LocalSession as SparkSession,
                                           Vectors)
    from sparkflow_tpu.model_loader import load_tensorflow_model
    from sparkflow_tpu.tensorflow_async import SparkAsyncDL

    mg_json, ckpt_prefix = make_legacy_artifacts()
    spark = SparkSession.builder.appName("tf1-migration").getOrCreate()
    rs = np.random.RandomState(0)
    rows = ([(1.0, Vectors.dense(rs.normal(2, 1, 2))) for _ in range(150)]
            + [(0.0, Vectors.dense(rs.normal(-2, 1, 2))) for _ in range(150)])
    df = spark.createDataFrame(rows, ["label", "features"])

    # 1) the reference's build_graph JSON trains as-is
    est = SparkAsyncDL(inputCol="features", tensorflowGraph=mg_json,
                       tfInput="x:0", tfLabel="y:0", tfOutput="out_act:0",
                       tfOptimizer="adam", tfLearningRate=0.1,
                       iters=5 if SMOKE else 25, partitions=2,
                       labelCol="label", predictionCol="predicted",
                       miniBatchSize=64)
    model = est.fit(df)
    errs = sum(1 for r in model.transform(df).collect()
               if round(float(r["predicted"])) != float(r["label"]))
    print(f"trained from raw MetaGraphDef JSON: {errs}/300 errors")

    # 2) the Saver checkpoint serves without a rebuilt graph
    served = load_tensorflow_model(ckpt_prefix, "features", "x:0",
                                   "out_act:0")
    n = served.transform(df).count()
    print(f"served {n} rows from the TF1 checkpoint's own .meta graph")
