"""BERT-base text classification at seq-len 512 — BASELINE.md's transformer
config (new capability; the reference has no sequence models).

Token ids travel as a Spark vector column; with real pyspark, tokenize with
Spark ML (`Tokenizer` + a vocab map) upstream — here synthetic ids keep the
example self-contained. On TPU this runs bf16 with the pallas flash-attention
kernel; CPU smoke mode shrinks the model.

Round-4 surfaces: set ``SPARKFLOW_TPU_MESH="dp=2,tp=4"`` to train the same
fit tensor-parallel from the Param surface (the sharded jit keeps the
pallas kernel via a nested shard_map), and the fitted model also serves an
int8-quantized transform for comparison.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

from sparkflow_tpu.models import build_registry_spec
from sparkflow_tpu.tensorflow_async import SparkAsyncDL
from sparkflow_tpu.compat import USING_PYSPARK

if USING_PYSPARK:
    from pyspark.sql import SparkSession
    from pyspark.ml.feature import OneHotEncoder
    from pyspark.ml.linalg import Vectors
    from pyspark.ml.pipeline import Pipeline
else:
    from sparkflow_tpu.localml import (LocalSession as SparkSession,
                                       OneHotEncoder, Pipeline, Vectors)

SMOKE = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))


def synthetic_text(spark, n, seq_len, vocab):
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(n):
        label = rs.randint(0, 2)
        ids = rs.randint(10, vocab, seq_len)
        if label:
            ids[:: 7] = 3  # a "positive" marker token pattern
        # variable-length documents: real tokens then padding, with the
        # attention mask travelling as its own column
        n_real = rs.randint(seq_len // 2, seq_len + 1)
        mask = np.zeros(seq_len)
        mask[:n_real] = 1.0
        ids[n_real:] = 0
        rows.append((float(label), Vectors.dense(ids.astype(float)),
                     Vectors.dense(mask)))
    return spark.createDataFrame(rows, ["label", "tokens", "mask"])


if __name__ == "__main__":
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    spark = SparkSession.builder.appName("bert-classifier").getOrCreate()
    seq_len = 64 if SMOKE else 512
    vocab = 1000 if SMOKE else 30522
    df = synthetic_text(spark, 256 if SMOKE else 4096, seq_len, vocab)

    spec = build_registry_spec(
        "transformer_classifier",
        vocab_size=vocab, num_classes=2,
        hidden=64 if SMOKE else 768,
        num_layers=2 if SMOKE else 12,
        num_heads=4 if SMOKE else 12,
        mlp_dim=128 if SMOKE else 3072,
        max_len=seq_len, dropout=0.1)

    est = SparkAsyncDL(
        inputCol="tokens",
        tensorflowGraph=spec,
        tfInput="input_ids:0",
        tfLabel="y:0",
        tfOutput="pred:0",
        tfOptimizer="adam",
        tfLearningRate=3e-4,
        iters=3 if SMOKE else 10,
        miniBatchSize=32,
        labelCol="labels",
        predictionCol="predicted",
        # multi-input feed: the attention mask rides a second column into a
        # second graph tensor (train AND transform)
        extraInputCols="mask",
        extraTfInputs="attention_mask:0",
        # optional multi-device mesh from the env (e.g. "dp=2,tp=4"); tp
        # uses the model's megatron rules, and attention keeps the pallas
        # kernel per shard
        **({"meshShape": os.environ["SPARKFLOW_TPU_MESH"]}
           if os.environ.get("SPARKFLOW_TPU_MESH") else {}))

    pipe = Pipeline(stages=[
        OneHotEncoder(inputCol="label", outputCol="labels", dropLast=False),
        est]).fit(df)
    preds = pipe.transform(df)
    acc = np.mean([float(r["predicted"]) == r["label"] for r in preds.collect()])
    print(f"train accuracy: {acc:.3f}")

    # int8 serving: same fitted model, weights quantized executor-side
    pipe.stages[-1].setParams(inferenceQuantize="weight_only")
    qpreds = pipe.transform(df)
    qacc = np.mean([float(r["predicted"]) == r["label"]
                    for r in qpreds.collect()])
    print(f"int8 (weight_only) serving accuracy: {qacc:.3f}")
