"""Attention kernels: flash (interpret mode on CPU) and ring vs reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sparkflow_tpu.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from sparkflow_tpu.ops import attention_reference, flash_attention, ring_attention


@pytest.fixture(scope="module")
def qkv():
    rs = np.random.RandomState(0)
    shape = (2, 2, 256, 64)
    return tuple(jnp.asarray(rs.randn(*shape), jnp.float32) for _ in range(3))


def test_flash_matches_reference(qkv):
    q, k, v = qkv
    ref = attention_reference(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_flash_causal_matches_reference(qkv):
    q, k, v = qkv
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_flash_attention_differentiable(qkv):
    """Regression: pallas kernels have no autodiff rule; the custom VJP must
    give reference-exact gradients (this crashed BERT training when missing)."""
    q, k, v = qkv
    for causal in (False, True):
        gf = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, interpret=True).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: attention_reference(
            a, b, c, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_flash_kv_mask_matches_reference(qkv):
    """The kernel's key-padding mask path (fwd + bwd) vs additive-mask ref."""
    q, _, _ = qkv
    rs = np.random.RandomState(7)
    mask = jnp.asarray((rs.rand(2, 256) > 0.3).astype(np.float32))

    def ref(qq):
        s = jnp.einsum("bhqd,bhkd->bhqk", qq, qq) / np.sqrt(qq.shape[-1])
        s = jnp.where(mask[:, None, None, :] > 0, s, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), qq)

    out = flash_attention(q, q, q, kv_mask=mask, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(q)), atol=1e-4)
    gf = jax.grad(lambda a: flash_attention(a, a, a, kv_mask=mask,
                                            interpret=True).sum())(q)
    gr = jax.grad(lambda a: ref(a).sum())(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=1e-3)


def test_flash_fallback_odd_shapes():
    """Non-tiling sequences take the jnp path and still match."""
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 2, 100, 32), jnp.float32)
    out = flash_attention(q, q, q)
    ref = attention_reference(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_attention_matches_reference(dp_mesh):
    """Ring attention over an 8-way sp ring == plain attention."""
    from jax.sharding import Mesh
    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(8), ("sp",))
    rs = np.random.RandomState(2)
    B, H, S, D = 2, 2, 64, 16
    q = jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, S, D), jnp.float32)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
        check_vma=False)
    out = jax.jit(ring)(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_attention_causal(dp_mesh):
    from jax.sharding import Mesh
    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(8), ("sp",))
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 2, 64, 16), jnp.float32)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
        check_vma=False)
    out = jax.jit(ring)(q, q, q)
    ref = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_force_xla_attention_skips_pallas(monkeypatch):
    """Sharded-jit programs must not hit the pallas kernel (no GSPMD
    partitioning rule); the guard context routes to the blockwise path."""
    import jax.numpy as jnp
    import pytest
    from sparkflow_tpu.ops import attention as A

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 1, 128, 8), jnp.float32)

    def boom(*a, **k):
        raise RuntimeError("pallas path taken")

    monkeypatch.setattr(A, "_flash", boom)
    # tiling-eligible shape: without the guard the kernel is attempted...
    with pytest.raises(RuntimeError, match="pallas path taken"):
        A.flash_attention(q, q, q)
    # ...and inside the guard context the XLA blockwise path runs instead
    with A.force_xla_attention():
        out = A.flash_attention(q, q, q)
    ref = A.attention_reference(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_last_attention_path_instrumentation():
    """Benchmarks assert the perf path via last_attention_path(); pin that
    the recorder distinguishes pallas / blockwise / reference routing."""
    import jax.numpy as jnp
    from sparkflow_tpu.ops import attention as A

    if A.pltpu is None:
        pytest.skip("pallas tpu backend unimportable in this build")
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 1, 128, 8), jnp.float32)

    A.flash_attention(q, q, q, interpret=True)  # tiling-eligible
    assert A.last_attention_path() == "pallas"

    with A.force_xla_attention():
        A.flash_attention(q, q, q)
    assert A.last_attention_path() == "blockwise"

    # odd head_dim breaks the d % 8 tile rule -> dense reference fallback
    qo = jnp.asarray(rs.randn(1, 1, 128, 6), jnp.float32)
    A.flash_attention(qo, qo, qo)
    assert A.last_attention_path() == "reference"


def test_flash_bwd_nonuniform_cotangent(qkv):
    """The pallas backward kernels (dq/dk/dv) under a structured cotangent —
    uniform .sum() grads can hide transposition errors."""
    q, k, v = qkv
    rs = np.random.RandomState(9)
    w = jnp.asarray(rs.randn(*q.shape), jnp.float32)
    for causal in (False, True):
        gf = jax.grad(lambda a, b, c: (flash_attention(
            a, b, c, causal=causal, interpret=True) * w).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: (attention_reference(
            a, b, c, causal=causal) * w).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3)


def test_flash_bwd_bf16():
    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(1, 2, 128, 64), jnp.bfloat16)
               for _ in range(3))
    gf = jax.grad(lambda a, b, c: flash_attention(
        a, b, c, interpret=True).astype(jnp.float32).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: attention_reference(
        a, b, c).astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=0.15)


def test_ring_flash_matches_ring_and_reference(dp_mesh):
    """ring_flash_attention (pallas per-visit blocks + lse merge) must equal
    plain ring attention and the dense reference, causal and not, fwd + bwd."""
    from sparkflow_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from sparkflow_tpu.ops import ring_flash_attention

    mesh = dp_mesh  # 8 devices, axis 'dp'
    rs = np.random.RandomState(0)
    B, H, S, D = 1, 2, 1024, 8  # S/8 = 128 per shard: kernel tiling holds
    q, k, v = (jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
               for _ in range(3))

    for causal in (False, True):
        def ring_fn(q, k, v):
            return ring_flash_attention(q, k, v, "dp", causal=causal)

        out = shard_map(ring_fn, mesh=mesh,
                        in_specs=(P(None, None, "dp", None),) * 3,
                        out_specs=P(None, None, "dp", None),
                        check_vma=False)(q, k, v)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, err_msg=f"causal={causal}")

        # gradients flow through the custom VJP (jnp-ring recompute)
        def loss(q, k, v):
            return shard_map(ring_fn, mesh=mesh,
                             in_specs=(P(None, None, "dp", None),) * 3,
                             out_specs=P(None, None, "dp", None),
                             check_vma=False)(q, k, v).sum()

        gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: attention_reference(
            a, b, c, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, err_msg=f"causal={causal}")


def test_ring_flash_kv_mask_path(dp_mesh):
    """The mask carry (mc rotating the ring into the kernel's mask BlockSpec)
    — the genuinely new data flow — causal and not."""
    from sparkflow_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from sparkflow_tpu.ops import ring_flash_attention

    rs = np.random.RandomState(4)
    B, H, S, D = 1, 2, 1024, 8
    q, k, v = (jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    mask = jnp.asarray((rs.rand(B, S) > 0.25).astype(np.float32))

    for causal in (False, True):
        def ring_fn(q, k, v, m):
            return ring_flash_attention(q, k, v, "dp", causal=causal,
                                        kv_mask=m)

        out = shard_map(ring_fn, mesh=dp_mesh,
                        in_specs=(P(None, None, "dp", None),) * 3
                        + (P(None, "dp"),),
                        out_specs=P(None, None, "dp", None),
                        check_vma=False)(q, k, v, mask)
        ref = attention_reference(q, k, v, causal=causal, kv_mask=mask)
        # masked rows that are fully excluded under causal+mask can differ
        # in garbage content; compare only rows with any visible key
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, err_msg=f"causal={causal}")

        # gradients through the pallas ring backward with the mask rotating
        # alongside the dk/dv accumulators
        def loss(a, b_, c):
            return shard_map(lambda q_, k_, v_, m_: ring_fn(q_, k_, v_, m_),
                             mesh=dp_mesh,
                             in_specs=(P(None, None, "dp", None),) * 3
                             + (P(None, "dp"),),
                             out_specs=P(None, None, "dp", None),
                             check_vma=False)(a, b_, c, mask).sum()

        gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b_, c: attention_reference(
            a, b_, c, causal=causal, kv_mask=mask).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-3,
                                       err_msg=f"mask grads causal={causal}")


def test_flash_block_specs_tile_legal():
    """Every pallas block mapping must satisfy the TPU tile rule: the last two
    block dims divisible by (8, 128) or equal to the array dims. The lse
    output / lse+delta operands and the kv mask used to travel as 2-D arrays
    with [1, block] blocks, which lowers fine in interpret mode but fails
    _check_block_mappings on real TPU hardware (caught live at BERT-512
    shapes). Row stats now travel as [bh, s, 1], the mask as [b, 1, sk];
    this pins the layout rule without needing a TPU."""
    from sparkflow_tpu.ops import attention as A

    def legal(block, array):
        for pos, (bdim, adim) in enumerate(zip(block[-2:], array[-2:])):
            div = (8, 128)[pos]  # sublane rule for dim -2, lane rule for -1
            if bdim != adim and bdim % div:
                return False
        return True

    bh, s, bq, bk, b, h = 6, 512, 128, 128, 2, 3
    # forward lse output layout
    assert legal((1, bq, 1), (bh, s, 1))
    # backward row-stat operands share the same layout
    spec = A._row_stat_spec(bq, "qk")
    assert spec.block_shape == (1, bq, 1)
    assert A._row_stat_spec(bq, "kq").index_map(4, 1, 2) == (4, 2, 0)
    # the kv mask travels [b, 1, sk] with [1, 1, block_k] blocks
    assert legal((1, 1, bk), (b, 1, s))
    # the old layouts are the regression: [1, block] over [bh, s] is illegal
    assert not legal((1, bq), (bh, s))


def test_flash_kv_mask_batched_rows(qkv):
    """Mask rows must be selected per batch (bh // h), exercising the 3-D
    [b, 1, sk] mask layout with b > 1 and distinct per-row masks."""
    q, k, v = qkv
    rs = np.random.RandomState(3)
    mask = jnp.asarray((rs.rand(q.shape[0], q.shape[2]) > 0.3)
                       .astype(np.float32))
    out = flash_attention(q, k, v, kv_mask=mask, interpret=True)
    ref = attention_reference(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g = jax.grad(lambda a: flash_attention(a, k, v, kv_mask=mask,
                                           interpret=True).sum())(q)
    gr = jax.grad(lambda a: attention_reference(a, k, v, kv_mask=mask)
                  .sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=3e-4)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="real-TPU pallas lowering check")
def test_flash_lowers_on_tpu():  # pragma: no cover (CPU suite skips)
    """Compile the non-interpret kernels at BERT-ish shapes: the exact path
    that failed the (8, 128) tile check before the 3-D row-stat layout."""
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(4, 12, 512, 64), jnp.float32)
    mask = jnp.asarray((rs.rand(4, 512) > 0.1).astype(np.float32))
    for causal in (False, True):
        for m in (None, mask):
            o = flash_attention(q, q, q, causal=causal, kv_mask=m,
                                interpret=False)
            r = attention_reference(q, q, q, causal=causal, kv_mask=m)
            assert float(jnp.linalg.norm((o - r).ravel())
                         / jnp.linalg.norm(r.ravel())) < 5e-3
            g = jax.grad(lambda a: flash_attention(
                a, a, a, causal=causal, kv_mask=m, interpret=False).sum())(q)
            assert bool(jnp.all(jnp.isfinite(g)))


def test_auto_block_selection():
    """Auto block choice: per-dimension, per-path, short-seq clamp."""
    from sparkflow_tpu.ops.attention import _auto_block

    assert _auto_block(4096, 1024) == 1024
    assert _auto_block(4096, 512) == 512
    assert _auto_block(384, 1024) == 128   # 384 = 3*128
    assert _auto_block(64, 1024) == 64     # short seq: the old min(128, s)
    assert _auto_block(320, 1024) == 128   # 320 % 128 != 0 -> kernel falls back


def test_flash_explicit_oversized_blocks_clamp_backward():
    """Explicit block_q/block_k larger than the sequence must clamp on the
    BACKWARD path too: an unclamped 512 at seq 256 makes the dq/dkv grids
    ``s // bwd_block == 0`` and the gradients come back unwritten."""
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(1, 2, 256, 32), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 256, 32), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 256, 32), jnp.float32)

    def loss(a, b_, c):
        return flash_attention(a, b_, c, block_q=512, block_k=512,
                               interpret=True).sum()

    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b_, c: attention_reference(a, b_, c).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-4)


def test_flash_short_query_cross_attention_keeps_kernel():
    """s=64 queries against sk=256 keys still runs the (interpret) pallas
    kernel via the short-seq clamp, matching the reference numerics."""
    import jax.numpy as jnp

    from sparkflow_tpu.ops import attention_reference, flash_attention

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 2, 64, 32), jnp.float32)
    kv = jnp.asarray(rs.randn(1, 2, 256, 32), jnp.float32)
    out = flash_attention(q, kv, kv, causal=False, interpret=True)
    ref = attention_reference(q, kv, kv, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_jit_attention_runs_pallas_per_shard(sharded_attn_mesh):
    """Sharded-jit traces no longer forfeit the flash kernel: under
    sharded_attention(mesh) the kernel runs per (batch x heads) shard via a
    nested shard_map, numerics identical to the blockwise path it replaces;
    shapes that don't divide the mesh fall back to blockwise."""
    import jax.numpy as jnp
    from sparkflow_tpu.ops import attention as A

    mesh = sharded_attn_mesh
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(4, 8, 128, 16), jnp.float32)  # b%2, h%4 divide

    with A.sharded_attention(mesh):
        out = jax.jit(lambda q: A.flash_attention(q, q, q, causal=True))(q)
    assert A.last_attention_path() == "pallas"
    ref = A.attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # gradients flow through the nested shard_map + custom vjp
    with A.sharded_attention(mesh):
        g = jax.jit(jax.grad(lambda q: A.flash_attention(
            q, q, q, causal=True).sum()))(q)
    gref = jax.grad(lambda q: A.attention_reference(
        q, q, q, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=2e-4, atol=2e-4)

    # heads (3) don't divide tp=4 -> blockwise fallback, not a raw custom
    # call GSPMD can't partition
    qo = jnp.asarray(rs.randn(4, 3, 128, 16), jnp.float32)
    with A.sharded_attention(mesh):
        out2 = jax.jit(lambda q: A.flash_attention(q, q, q))(qo)
    assert A.last_attention_path() == "blockwise"
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(A.attention_reference(qo, qo, qo)),
                               rtol=2e-5, atol=2e-5)


def test_sharded_jit_attention_with_kv_mask(sharded_attn_mesh):
    """The key-padding mask shards over the batch axis with q/k/v: masked
    sharded-jit attention (the BERT attention_mask path on a mesh) runs the
    pallas kernel per shard — forward AND backward — and matches the
    reference."""
    import jax.numpy as jnp
    from sparkflow_tpu.ops import attention as A

    mesh = sharded_attn_mesh
    rs = np.random.RandomState(4)
    q = jnp.asarray(rs.randn(4, 8, 128, 16), jnp.float32)
    mask = jnp.asarray((rs.rand(4, 128) > 0.3).astype(np.float32))

    with A.sharded_attention(mesh):
        out = jax.jit(lambda q, m: A.flash_attention(q, q, q, kv_mask=m))(
            q, mask)
    # the masked wrap must keep the kernel, not silently fall to blockwise
    # (which also honors the mask and would match numerically)
    assert A.last_attention_path() == "pallas"
    ref = A.attention_reference(q, q, q, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # masked custom-vjp under shard_map (the has_mask backward kernels with
    # sharded operands) — only tested unsharded elsewhere
    with A.sharded_attention(mesh):
        g = jax.jit(jax.grad(lambda q: A.flash_attention(
            q, q, q, kv_mask=mask).sum()))(q)
    gref = jax.grad(lambda q: A.attention_reference(
        q, q, q, kv_mask=mask).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=2e-4, atol=2e-4)
