"""XLA collectives: the framework's gradient/weight transport layer.

The TPU-native replacement for the reference's HTTP weight/gradient
transport (``GET /parameters`` / ``POST /update``,
``sparkflow/HogwildSparkModel.py:22-35``): gradient merge is a ``psum``
compiled into the train step, riding ICI/DCN — weights never leave the
device mesh. Besides the named one-liners (kept as the vocabulary the step
builders share), :func:`hierarchical_psum_mean` is the pod-scale form:
a topology-aware two-level reduction whose cross-slice DCN hop carries only
``1/n_ici`` of the gradient bytes (used by
``parallel.dp.make_dp_shardmap_train_step(dcn_axis=...)``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..jax_compat import axis_size


def psum_mean(tree, axis_name: str):
    """All-reduce-mean a pytree over a mesh axis (gradient averaging)."""
    n = jax.lax.psum(1, axis_name)
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name) / n, tree)


def psum(tree, axis_name: str):
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_ring(x, axis_name: str, shift: int = 1):
    """Rotate shards around the mesh-axis ring (building block of ring
    attention and pipeline schedules)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def hierarchical_psum_mean(tree, ici_axis: str, dcn_axis: str):
    """Two-level gradient mean for multi-slice meshes (mesh axes ordered
    [dcn, ici]): per leaf, ``psum_scatter`` over the fast intra-slice ICI
    axis, all-reduce the 1/n_ici-sized shard over the slow cross-slice DCN
    axis, then ``all_gather`` back over ICI.

    Mathematically equivalent to a flat ``psum`` over both axes divided by
    the total device count; bitwise differences are possible because the
    reduction order changes, and stay bounded by the pinned tolerance in the
    parity tests. The point is the WIRE layout: the DCN hop (tens of
    GB/s across slices, vs ~100s of GB/s ICI within one) carries only
    ``1/n_ici`` of the gradient bytes, instead of the full tree a flat
    cross-axis psum would move. This is the standard pod-scale data-parallel
    reduction (scaling-book §sharding: reduce_scatter -> cross-slice
    all-reduce -> all_gather).

    Must run inside ``shard_map`` with both axes bound. Leaves whose size
    does not divide ``n_ici`` are flat-padded for the scatter and unpadded
    after the gather (exactness unaffected: padding reduces to zeros).
    """
    n_ici = axis_size(ici_axis)
    total = n_ici * axis_size(dcn_axis)

    def leaf(x):
        flat = jnp.ravel(x)
        pad = (-flat.size) % n_ici
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        shard = jax.lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                                     tiled=True)
        shard = jax.lax.psum(shard, dcn_axis)  # 1/n_ici of the bytes on DCN
        out = jax.lax.all_gather(shard, ici_axis, axis=0, tiled=True)
        if pad:
            out = out[:x.size]
        return (out / total).reshape(x.shape).astype(x.dtype)

    return jax.tree.map(leaf, tree)
