"""graftcheck (sparkflow_tpu.analysis): planted-defect detection per rule,
zero false positives on the repo's own code, and the serving/trainer
integrations.

Two invariants this file pins:

- every analyzer catches a deliberately planted defect and reports the
  documented rule id;
- the repo lints CLEAN under its own full pass (``python -m
  sparkflow_tpu.analysis sparkflow_tpu examples`` exits 0) — the static
  rules over every source file plus the jaxpr self-check over the model
  presets x the optimizer registry.
"""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.analysis import (RecompileGuard, RULES, ast_lint, locks,
                                    track_recompiles)
from sparkflow_tpu.analysis.cli import main as cli_main, run_static
from sparkflow_tpu.analysis.findings import Finding, filter_suppressed
from sparkflow_tpu.analysis.jaxpr_lint import (lint_fn, lint_train_step,
                                               repo_self_check)
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.models import model_from_json, presets
from sparkflow_tpu.optimizers import AVAILABLE_OPTIMIZERS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# jaxpr_lint: planted defects (GC-J1xx)
# ---------------------------------------------------------------------------


def test_j101_implicit_reshard_detected(dp_mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(dp_mesh, P())

    def f(x):
        # declared P('dp') below, pinned replicated here -> GSPMD reshard
        return jax.lax.with_sharding_constraint(x, repl) * 2.0

    x = jax.ShapeDtypeStruct((8, 4), np.float32)
    fs = lint_fn(f, (x,), in_specs=(P("dp"),), mesh=dp_mesh)
    assert "GC-J101" in rules_of(fs)
    # aligned constraint: clean
    sharded = NamedSharding(dp_mesh, P("dp"))
    g = lambda x: jax.lax.with_sharding_constraint(x, sharded) * 2.0
    assert "GC-J101" not in rules_of(
        lint_fn(g, (x,), in_specs=(P("dp"),), mesh=dp_mesh))


def test_j102_large_replicated_detected(dp_mesh):
    from jax.sharding import PartitionSpec as P

    x = jax.ShapeDtypeStruct((1024, 512), np.float32)  # 2 MiB
    fs = lint_fn(lambda x: x.sum(), (x,), in_specs=(P(),), mesh=dp_mesh)
    assert "GC-J102" in rules_of(fs)
    # sharded placement of the same tensor: clean
    assert "GC-J102" not in rules_of(
        lint_fn(lambda x: x.sum(), (x,), in_specs=(P("dp"),), mesh=dp_mesh))


def test_j103_f64_promotion_detected():
    def f(x):
        return x * np.float64(1.5)  # strong double on the hot path

    x = jax.ShapeDtypeStruct((4, 4), np.float32)
    fs = lint_fn(f, (x,))
    assert "GC-J103" in rules_of(fs)
    # weak Python literals do NOT promote: clean
    assert "GC-J103" not in rules_of(lint_fn(lambda x: x * 1.5, (x,)))


def test_j104_weak_type_output_detected():
    x = jax.ShapeDtypeStruct((4,), np.float32)
    fs = lint_fn(lambda x: jnp.exp(2.0), (x,))  # scalar-dominated output
    assert "GC-J104" in rules_of(fs)
    assert "GC-J104" not in rules_of(lint_fn(lambda x: jnp.exp(x), (x,)))


def test_j105_missed_donation_detected():
    x = jax.ShapeDtypeStruct((1024, 512), np.float32)  # 2 MiB

    def f(x):
        return x * 2.0  # output aval == input aval

    assert "GC-J105" in rules_of(lint_fn(f, (x,)))
    # donated: clean
    assert "GC-J105" not in rules_of(lint_fn(f, (x,), donate_argnums=(0,)))
    # small tensors are never donation findings
    small = jax.ShapeDtypeStruct((4, 4), np.float32)
    assert "GC-J105" not in rules_of(lint_fn(f, (small,)))


def test_lint_train_step_runs_on_preset():
    mlp = model_from_json(presets.mlp(6, 3, hidden=(4,)))
    assert lint_train_step(mlp, "x:0", "y:0", "adam", batch=4) == []


def test_j108_full_pool_dequant_both_directions():
    """GC-J108 fires on a step that widens the ENTIRE quantized KV pool to
    float before gathering pages, stays quiet when the convert runs on the
    gathered pages only (the dequant-on-read idiom), and honors ignore."""
    from sparkflow_tpu.analysis.jaxpr_lint import lint_decode_collectives

    NUM_PAGES, page, h, d = 33, 8, 4, 8
    pool = jax.ShapeDtypeStruct((2, NUM_PAGES, page, h, d), jnp.int8)
    scales = jax.ShapeDtypeStruct((2, NUM_PAGES, h), np.float32)
    table = jax.ShapeDtypeStruct((4, 2), np.int32)

    def bad_step(kp, sc, t):
        # the planted defect: dequantize the whole pool, then gather
        full = kp.astype(jnp.float32) * sc[:, :, None, :, None]
        return full[0][t]

    found = lint_decode_collectives(bad_step, (pool, scales, table),
                                    kv_pool_pages=NUM_PAGES)
    assert any(f.rule == "GC-J108" for f in found), found
    f = next(f for f in found if f.rule == "GC-J108")
    assert f.detail["kv_pool_pages"] == NUM_PAGES

    def good_step(kp, sc, t):
        # dequant-on-read: convert only the gathered pages
        g = kp[0][t].astype(jnp.float32)
        return g * sc[0][t][:, :, None, :, None]

    assert lint_decode_collectives(good_step, (pool, scales, table),
                                   kv_pool_pages=NUM_PAGES) == []
    # without a quantized pool declared, the scan is off entirely
    assert lint_decode_collectives(bad_step, (pool, scales, table)) == []
    # and the ignore escape hatch silences it
    assert lint_decode_collectives(bad_step, (pool, scales, table),
                                   kv_pool_pages=NUM_PAGES,
                                   ignore=("GC-J108",)) == []


def test_j108_quantized_engine_repo_clean():
    """The repo's own int8 decode step never materializes the float pool:
    lint_decode_step wires kv_pool_pages automatically for a quantized
    engine and must come back empty."""
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json as _mfj)
    from sparkflow_tpu.serving import DecodeEngine
    from sparkflow_tpu.analysis.jaxpr_lint import lint_decode_step

    spec = build_registry_spec("transformer_lm", vocab_size=61, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    m = _mfj(spec)
    eng = DecodeEngine(m, m.init(jax.random.PRNGKey(0)), num_slots=4,
                       page_size=8, seed=0, kv_quant="int8", warmup=False)
    assert lint_decode_step(eng) == []


# ---------------------------------------------------------------------------
# ast_lint: planted defects (GC-A2xx)
# ---------------------------------------------------------------------------


def test_a201_host_sync_in_jit_detected():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            v = float(x)          # concretizes a tracer
            print(x)              # trace-time print
            return x.item() + v   # host sync
    """)
    fs = [f for f in ast_lint.lint_source(src) if f.rule == "GC-A201"]
    assert len(fs) == 3
    assert all("step" in f.message for f in fs)


def test_a201_np_asarray_on_traced_arg():
    src = textwrap.dedent("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x) + 1
    """)
    assert "GC-A201" in rules_of(ast_lint.lint_source(src))


def test_a202_traced_branch_detected():
    src = textwrap.dedent("""
        import jax

        def step(x):
            if x > 0:
                return x
            return -x

        fast = jax.jit(step)
    """)
    fs = [f for f in ast_lint.lint_source(src) if f.rule == "GC-A202"]
    assert len(fs) == 1 and "'x'" in fs[0].message


def test_a202_static_checks_exempt():
    # is-None / isinstance / hasattr / len / .shape tests are all static
    # under jit: branching on them is fine and must not be flagged
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x, mask=None):
            if mask is None:
                return x
            if isinstance(x, tuple):
                x = x[0]
            if hasattr(x, "nope"):
                return x
            if x.ndim == 2 and x.shape[0] > 4 and len(x) > 2:
                return x * mask
            return x + mask
    """)
    assert "GC-A202" not in rules_of(ast_lint.lint_source(src))


def test_a202_tree_map_callback_not_traced():
    # jax.tree.map is not a tracing transform: branching inside its
    # callback on a (typically static-leaf) argument is not a finding
    src = textwrap.dedent("""
        import jax

        def pick(spec):
            if spec == "big":
                return 1
            return 0

        out = jax.tree.map(pick, {"a": "big"})
    """)
    assert ast_lint.lint_source(src) == []


def test_local_assignment_shadows_method_name():
    # the serving-engine pattern: a method jits a LOCAL callable that
    # shares the name of a host-side method; the method is not traced
    src = textwrap.dedent("""
        import jax

        class Engine:
            def predict(self, x):
                return float(x)  # host-side: allowed

            def _compile(self):
                predict = self._apply_fn()
                return jax.jit(predict)
    """)
    assert ast_lint.lint_source(src) == []


def test_a203_prng_key_reuse_detected():
    src = textwrap.dedent("""
        import jax

        def sample(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
    """)
    fs = [f for f in ast_lint.lint_source(src) if f.rule == "GC-A203"]
    assert len(fs) == 1 and "'key'" in fs[0].message


def test_a203_split_and_rebind_clean():
    src = textwrap.dedent("""
        import jax

        def sample(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (4,))
            b = jax.random.uniform(k2, (4,))
            key = jax.random.fold_in(key, 7)
            c = jax.random.normal(key, (4,))
            return a + b + c
    """)
    assert "GC-A203" not in rules_of(ast_lint.lint_source(src))


def test_a203_exclusive_branches_clean_loop_reuse_caught():
    clean = textwrap.dedent("""
        import jax

        def sample(key, flag):
            if flag:
                return jax.random.normal(key, (4,))
            return jax.random.uniform(key, (4,))
    """)
    assert "GC-A203" not in rules_of(ast_lint.lint_source(clean))
    loop = textwrap.dedent("""
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.normal(key, (4,)))
            return out
    """)
    assert "GC-A203" in rules_of(ast_lint.lint_source(loop))


def test_a204_unhashable_static_default_detected():
    src = textwrap.dedent("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def apply(x, dims=[1, 2]):
            return x.reshape(dims)
    """)
    fs = [f for f in ast_lint.lint_source(src) if f.rule == "GC-A204"]
    assert len(fs) == 1 and "'dims'" in fs[0].message
    # tuple default: hashable, clean
    ok = src.replace("[1, 2]", "(1, 2)")
    assert "GC-A204" not in rules_of(ast_lint.lint_source(ok))


# ---------------------------------------------------------------------------
# locks: planted defects (GC-L3xx)
# ---------------------------------------------------------------------------

_LOCKED_CLASS = textwrap.dedent("""
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            self.hits = 0

        def add(self, v):
            with self._lock:
                self.n += 1

        def race(self, v):
            self.n = 0          # guarded attr written without the lock
            self.hits += v      # rmw on unguarded shared state
""")


def test_l301_l302_detected():
    fs = locks.lint_source(_LOCKED_CLASS)
    assert rules_of(fs) == {"GC-L301", "GC-L302"}
    by_rule = {f.rule: f for f in fs}
    assert "self.n" in by_rule["GC-L301"].message
    assert "self.hits" in by_rule["GC-L302"].message


def test_locked_suffix_helper_convention():
    # a *_locked helper's body scans as lock-held (no L301/L302 inside it);
    # the enforcement moves to call sites: locked call clean, unlocked call
    # flagged as GC-L303
    src = textwrap.dedent("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.evictions = 0

            def _sweep_locked(self):
                self.evictions += 1   # fine: caller holds the lock

            def tick(self):
                with self._lock:
                    self._sweep_locked()

            def broken(self):
                self._sweep_locked()  # GC-L303: no lock held
    """)
    fs = locks.lint_source(src)
    assert rules_of(fs) == {"GC-L303"}
    (f,) = fs
    assert "broken" in f.message and "_sweep_locked" in f.message


def test_lock_free_class_and_init_exempt():
    # no lock attribute -> the class never opted into the rules; and
    # __init__ writes are exempt even in lock-owning classes
    src = textwrap.dedent("""
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
    """)
    assert locks.lint_source(src) == []
    assert not any(f.line <= 8 for f in locks.lint_source(_LOCKED_CLASS))


# ---------------------------------------------------------------------------
# runtime guards (GC-R401)
# ---------------------------------------------------------------------------


def test_recompile_guard_counts_and_names_cause():
    guard = RecompileGuard(lambda x: x * 2, name="double")
    guard(jnp.ones((4,)))
    guard(jnp.ones((4,)))        # cache hit: no new trace
    assert guard.traces == 1 and guard.retraces == 0
    assert guard.findings() == []
    guard(jnp.ones((8,)))        # shape change: retrace
    guard(jnp.ones((8,), jnp.int32))  # dtype change: retrace
    assert guard.traces == 3
    fs = guard.findings()
    assert rules_of(fs) == {"GC-R401"}
    causes = "\n".join(guard.causes)
    assert "[4]" in causes and "[8]" in causes and "int32" in causes


def test_recompile_guard_wrap_and_mark_steady():
    guard = RecompileGuard(name="aot")
    fn = jax.jit(guard.wrap(lambda x: x + 1))
    fn(jnp.ones((2,)))
    guard.mark_steady()
    assert guard.steady_traces == 0 and guard.findings() == []
    fn(jnp.ones((3,)))           # post-steady trace: a regression
    assert guard.steady_traces == 1
    assert "GC-R401" in rules_of(guard.findings())


def test_track_recompiles_sees_core_train_step():
    from sparkflow_tpu import core
    from sparkflow_tpu.optimizers import build_optimizer

    model = model_from_json(presets.mlp(4, 2, hidden=(3,)))
    loss_fn = core.make_loss_fn(model, "x:0", "y:0")
    opt = build_optimizer("gradient_descent", 0.1)
    params = model.init(jax.random.PRNGKey(0))
    state = opt.init(params)
    rng = jax.random.PRNGKey(1)

    def batch(n):
        return (jnp.zeros((n, 4)), jnp.zeros((n, 2)), jnp.ones((n,)))

    with track_recompiles() as tracker:
        # params/opt state are donated by the step: re-thread them
        step = core.make_train_step(loss_fn, opt)
        x, y, m = batch(8)
        params, state, _ = step(params, state, x, y, m, rng)
        x, y, m = batch(8)
        params, state, _ = step(params, state, x, y, m, rng)  # cache hit
        assert tracker.traces == {"train_step": 1}
        x, y, m = batch(16)
        params, state, _ = step(params, state, x, y, m, rng)  # ragged batch
    assert tracker.traces["train_step"] == 2
    fs = tracker.findings()
    assert rules_of(fs) == {"GC-R401"}
    assert "16" in tracker.report()


def test_trainer_debug_recompiles_populates_report():
    from sparkflow_tpu.trainer import Trainer

    tr = Trainer(presets.mlp(4, 2, hidden=(3,)), "x:0", "y:0", iters=2,
                 mini_batch_size=8, debug_recompiles=True)
    rs = np.random.RandomState(0)
    tr.fit(rs.rand(16, 4).astype(np.float32),
           np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)])
    assert tr.recompile_report is not None
    assert "trace" in tr.recompile_report
    # a fixed-shape fit compiles each program once: no findings
    assert tr.recompile_findings == []


# ---------------------------------------------------------------------------
# serving integration: AOT ladder serves every size with zero retraces
# ---------------------------------------------------------------------------


def _serving_graph():
    def g():
        x = nn.placeholder([None, 4], name="x")
        h = nn.dense(x, 3, activation="relu")
        out = nn.dense(h, 2, name="out")
        nn.mean_squared_error(x, out)
    return build_graph(g)


def test_engine_zero_retraces_after_warmup():
    from sparkflow_tpu.serving import InferenceEngine

    rs = np.random.RandomState(0)
    weights = [rs.randn(4, 3).astype(np.float32),
               rs.randn(3).astype(np.float32),
               rs.randn(3, 2).astype(np.float32),
               rs.randn(2).astype(np.float32)]
    eng = InferenceEngine(_serving_graph(), weights, input_name="x:0",
                          output_name="out/BiasAdd:0", max_batch=8)
    stats = eng.stats()
    # warmup compiled exactly the ladder, one guard trace per bucket
    assert stats["traces"] == stats["aot_compiles"] == len(eng.buckets)
    assert stats["steady_traces"] == 0
    # every request size 1..max_batch (plus a chunked oversize request)
    # serves from the compiled ladder: no new traces, no fallback compiles
    for n in list(range(1, 9)) + [11]:
        out = eng.predict(rs.randn(n, 4).astype(np.float32))
        assert out.shape == (n, 2)
    stats = eng.stats()
    assert stats["steady_traces"] == 0
    assert stats["fallback_compiles"] == 0
    assert stats["requests"] == 9 and stats["rows"] == sum(range(1, 9)) + 11
    assert eng.recompile_guard.findings() == []


# ---------------------------------------------------------------------------
# dtype stability (satellite): presets x optimizer registry stay f32-pure
# ---------------------------------------------------------------------------


def test_optimizer_registry_dtype_stable():
    """No registry optimizer may introduce f64 (even latently, under an
    x64 flip) or weakly-typed outputs into the train step."""
    mlp = model_from_json(presets.mlp(6, 3, hidden=(4,)))
    for opt in AVAILABLE_OPTIMIZERS:
        fs = lint_train_step(mlp, "x:0", "y:0", opt, batch=4)
        bad = [f for f in fs if f.rule in ("GC-J103", "GC-J104")]
        assert not bad, f"{opt}: {[f.render() for f in bad]}"


# ---------------------------------------------------------------------------
# jax_compat shim under the linters (no false positives)
# ---------------------------------------------------------------------------


def test_jax_compat_clean_under_static_pass():
    path = os.path.join(REPO, "sparkflow_tpu", "jax_compat.py")
    assert ast_lint.lint_file(path) == []
    assert locks.lint_file(path) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_trailing_suppression_drops_finding():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            if x > 0:  # graftcheck: disable=GC-A202
                return x
            return -x
    """)
    assert ast_lint.lint_source(src) == []
    # wrong rule id on the comment: the finding survives
    other = src.replace("GC-A202", "GC-A201")
    assert "GC-A202" in rules_of(ast_lint.lint_source(other))


def test_file_wide_suppression_only_in_header():
    body = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
    """)
    header = "# graftcheck: disable-file=GC-A202\n"
    assert ast_lint.lint_source(header + body) == []
    # beyond the first ten lines the directive is ignored
    late = body + "\n\n" + header
    assert "GC-A202" in rules_of(ast_lint.lint_source(late))


def test_filter_suppressed_matches_line():
    f = Finding("GC-A201", "msg", path="x.py", line=2)
    src = "a = 1\nb = 2  # graftcheck: disable=GC-A201\n"
    assert filter_suppressed([f], src) == []
    assert filter_suppressed([Finding("GC-A201", "msg", path="x.py",
                                      line=1)], src) != []


# ---------------------------------------------------------------------------
# the repo is clean under its own linter (tentpole acceptance)
# ---------------------------------------------------------------------------


def test_repo_static_pass_clean():
    paths = [os.path.join(REPO, "sparkflow_tpu"),
             os.path.join(REPO, "examples")]
    fs = run_static(paths)
    assert fs == [], "\n" + "\n".join(f.render() for f in fs)


def test_repo_jaxpr_self_check_clean():
    fs = repo_self_check()
    assert fs == [], "\n" + "\n".join(f.render() for f in fs)


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n")
    assert cli_main([str(bad), "--no-trace"]) == 1
    out = capsys.readouterr().out
    assert "GC-A201" in out
    good = tmp_path / "good.py"
    good.write_text("def f(x):\n    return x\n")
    assert cli_main([str(good), "--no-trace"]) == 0
    assert cli_main(["--list-rules"]) == 0
    listing = capsys.readouterr().out
    for rule in RULES:
        assert rule in listing


def test_cli_json_format(tmp_path, capsys):
    # JSONL contract: ONE finding object per line, so CI/editors can
    # stream-parse and grep; a clean run emits nothing on stdout
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n")
    assert cli_main([str(bad), "--no-trace", "--format", "json"]) == 1
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    payload = [json.loads(ln) for ln in lines]  # every line parses alone
    assert payload and payload[0]["rule"] == "GC-A201"
    assert {"rule", "name", "path", "line", "source", "message"} \
        <= set(payload[0])
    assert cli_main([str(bad), "--no-trace", "--ignore", "GC-A201"]) == 0
    capsys.readouterr()
    good = tmp_path / "good.py"
    good.write_text("def f(x):\n    return x\n")
    assert cli_main([str(good), "--no-trace", "--format", "json"]) == 0
    assert capsys.readouterr().out.strip() == ""


# ---------------------------------------------------------------------------
# policy_lint: planted defects both directions + repo-clean gate (GC-S501)
# ---------------------------------------------------------------------------


def test_s501_impure_policy_detected():
    # planted defects: every category of impurity inside a marked module
    # must be flagged with the documented rule id
    from sparkflow_tpu.analysis import policy_lint

    src = textwrap.dedent("""\
        # graftcheck: pure-policy
        import time
        import random as rnd
        from socket import create_connection

        def decide(views):
            now = time.monotonic()
            coin = rnd.random()
            create_connection(("h", 80))
            open("/tmp/x")
            client.sleep(1.0)
            return now + coin
    """)
    fs = policy_lint.lint_source(src, "planted.py")
    assert fs and rules_of(fs) == {"GC-S501"}
    lines = {f.line for f in fs}
    # imports (2, 3, 4), time call (7), random call (8), socket call (9),
    # open (10), .sleep (11)
    assert {2, 3, 4, 7, 8, 9, 10, 11} <= lines


def test_s501_clean_and_unmarked_not_flagged():
    # the other direction: pure code in a marked module is clean, and an
    # unmarked module may be as impure as it likes (out of scope)
    from sparkflow_tpu.analysis import policy_lint

    pure = textwrap.dedent("""\
        # graftcheck: pure-policy
        from dataclasses import dataclass

        def pick(views, now, prefer_canary):
            return sorted(v.index for v in views if v.healthy)
    """)
    assert policy_lint.lint_source(pure, "pure.py") == []
    impure_unmarked = "import time\n\ndef f():\n    return time.time()\n"
    assert policy_lint.lint_source(impure_unmarked, "um.py") == []
    # standard suppression syntax applies
    suppressed = textwrap.dedent("""\
        # graftcheck: pure-policy
        import time  # graftcheck: disable=GC-S501

        def f(x):
            return x
    """)
    assert policy_lint.lint_source(suppressed, "sup.py") == []


def test_s501_policy_module_repo_clean():
    # the real policy module carries the marker and must stay pure; the
    # full static pass (which now includes policy_lint) agrees
    from sparkflow_tpu.analysis import policy_lint
    from sparkflow_tpu.serving import policies as policies_mod

    path = policies_mod.__file__
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    assert policy_lint.PURE_POLICY_MARKER in src.splitlines()[0]
    assert policy_lint.lint_file(path) == []
    assert [f for f in run_static([path])] == []
