"""Crash flight recorder: always-on, bounded, SIGKILL-survivable.

Crash replacement (PR 18's ``Autoscaler``/``ReplicaManager``) keeps the
fleet serving through a replica death — but the dead process takes its
in-memory tracer ring and metrics with it, so the crash is unexplainable
postmortem. :class:`FlightRecorder` closes that gap with two write paths
of different durability:

- **Begin/end event lines** are appended *and flushed* to the JSONL file
  the moment a request enters / leaves the process. SIGKILL runs no
  handlers, so the only evidence that can survive it is evidence already
  on disk — replaying begins-without-ends names exactly the trace ids
  that were in flight when the process died.
- The **full dump** — recent spans (bounded), metric *deltas* since
  install, the live in-flight set — is appended on SIGTERM and atexit,
  the cases where the process does get a last word.

:func:`harvest_flight` parses a (possibly truncated — the process may
have died mid-write) recorder file back into one postmortem record;
``ReplicaManager`` calls it when it reaps or destroys a dead replica and
logs the in-flight trace ids.

The file is bounded: matched begin/end pairs are compacted away once the
event count passes a threshold, so an always-on recorder in a months-long
replica stays a few KB, not a log that grows without limit (the same
contract as the span ring's ``MAX_SPANS``).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.metrics import Metrics, default_metrics
from .collector import normalize_span
from .spans import Tracer, default_tracer

__all__ = ["FlightRecorder", "harvest_flight"]

#: spans included in a dump (most recent first in time order)
MAX_DUMP_SPANS = 512

#: begin/end lines on disk before matched pairs are compacted away
COMPACT_THRESHOLD = 4096


class FlightRecorder:
    """Bounded request-event log + last-word dump for one process.

    ``path`` is this process's recorder file (the fleet convention is
    ``<flight_dir>/replica-<port>.jsonl`` so the manager can find it by
    port). ``install()`` arms the SIGTERM chain (previous handler — e.g.
    the server's drain — still runs after the dump) and the atexit hook;
    ``close()`` disarms both and closes the file. All methods are
    thread-safe and never raise out of the signal path."""

    def __init__(self, path: str, *, tracer: Optional[Tracer] = None,
                 metrics: Optional[Metrics] = None,
                 max_dump_spans: int = MAX_DUMP_SPANS):
        self.path = path
        self.tracer = tracer if tracer is not None else default_tracer
        self.metrics = metrics if metrics is not None else default_metrics
        self.max_dump_spans = int(max_dump_spans)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # unbuffered binary append: every line is one write(2) straight to
        # disk — the durability the begin/end path needs, without paying a
        # buffered-writer flush on the serving hot path (~3x cheaper)
        self._f = open(path, "ab", buffering=0)
        self._inflight: Dict[str, float] = {}
        self._events = 0
        self._dumped = False
        self._baseline = self.metrics.counters()
        self._prev_sigterm: Any = None
        self._signal_installed = False
        self._atexit_installed = False
        self._write({"event": "open", "process": self.tracer.fingerprint,
                     "pid": os.getpid(), "ts": time.time()})

    # -- event log (SIGKILL-survivable path) ---------------------------------

    def _write_line(self, line: str) -> None:
        """Append one pre-serialized JSON line. Caller holds the lock (or
        is the constructor, before the recorder is shared)."""
        f = self._f
        if f is None:
            return
        try:
            f.write((line + "\n").encode("utf-8"))
        except (OSError, ValueError):
            pass  # a full disk must never take the serving path down

    def _write(self, rec: Dict[str, Any]) -> None:
        self._write_line(json.dumps(rec))

    def begin(self, trace_id: str,
              request_id: Optional[str] = None) -> None:
        """A request with ``trace_id`` entered this process."""
        # hand-formatted on the hot path (json.dumps of the whole record
        # costs more than the write itself); request_id is user-supplied
        # so only IT goes through the serializer
        ts = time.time()
        line = '{"event":"begin","trace_id":%s,"ts":%r}' % (
            json.dumps(trace_id), ts)
        if request_id:
            line = '%s,"request_id":%s}' % (line[:-1], json.dumps(request_id))
        with self._lock:
            self._inflight[trace_id] = ts
            self._events += 1
            self._write_line(line)

    def end(self, trace_id: str, error: bool = False) -> None:
        """The request left (completed or failed — either way it is no
        longer in flight, so its begin/end pair is compactable)."""
        line = '{"event":"end","trace_id":%s,"ts":%r%s}' % (
            json.dumps(trace_id), time.time(),
            ',"error":true' if error else "")
        with self._lock:
            self._inflight.pop(trace_id, None)
            self._events += 1
            self._write_line(line)
            if self._events >= COMPACT_THRESHOLD:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the file keeping only still-open begins (+ any dump
        lines), then atomically replace — bounds the always-on log."""
        f = self._f
        if f is None:
            return
        tmp = f"{self.path}.tmp-{os.getpid()}"
        try:
            kept: List[str] = []
            with open(self.path) as src:
                for line in src:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    ev = rec.get("event")
                    if ev == "begin" and rec.get("trace_id") in self._inflight:
                        kept.append(line)
                    elif ev in ("open", "dump"):
                        kept.append(line)
            with open(tmp, "w") as out:
                out.writelines(kept)
            f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab", buffering=0)
            self._events = len(kept)
        except (OSError, ValueError):
            # compaction is best-effort; keep appending to the old handle
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def inflight(self) -> List[str]:
        with self._lock:
            return sorted(self._inflight)

    # -- the last word (SIGTERM / atexit path) -------------------------------

    def dump(self, reason: str = "manual", force: bool = False) -> str:
        """Append the full postmortem record: recent spans, metric deltas
        since construction, the in-flight set. Idempotent unless ``force``
        (SIGTERM then atexit should not double-dump). Returns the path."""
        spans = self.tracer.spans()[-self.max_dump_spans:]
        records = [normalize_span(self.tracer, s) for s in spans]
        counters = self.metrics.counters()
        deltas = {}
        for name, value in counters.items():
            d = value - self._baseline.get(name, 0.0)
            if d:
                deltas[name] = d
        with self._lock:
            if self._dumped and not force:
                return self.path
            self._dumped = True
            self._write({"event": "dump", "reason": reason,
                         "process": self.tracer.fingerprint,
                         "pid": os.getpid(), "ts": time.time(),
                         "inflight": sorted(self._inflight),
                         "spans": records, "metric_deltas": deltas})
        return self.path

    # -- arming --------------------------------------------------------------

    def install(self, signals=(signal.SIGTERM,)) -> "FlightRecorder":
        """Arm the atexit hook, and (main thread only — ``signal.signal``
        raises elsewhere) chain a dump in front of the existing handler
        for each of ``signals``."""
        if not self._atexit_installed:
            atexit.register(self._atexit_dump)
            self._atexit_installed = True
        for sig in signals:
            try:
                prev = signal.signal(sig, self._on_signal)
            except ValueError:
                break  # not the main thread; atexit still covers us
            if sig == signal.SIGTERM:
                self._prev_sigterm = prev
                self._signal_installed = True
        return self

    def _on_signal(self, signum, frame) -> None:
        self.dump(reason=f"signal:{signum}")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore and re-raise so default termination still happens
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="atexit")
        except Exception:
            pass  # interpreter teardown: never raise into atexit

    def close(self) -> None:
        """Disarm hooks, restore the previous SIGTERM handler, close the
        file. Idempotent."""
        if self._atexit_installed:
            try:
                atexit.unregister(self._atexit_dump)
            except Exception:
                pass
            self._atexit_installed = False
        if self._signal_installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, TypeError):
                pass
            self._signal_installed = False
        with self._lock:
            f = self._f
            self._f = None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def harvest_flight(path: str) -> Optional[Dict[str, Any]]:
    """Parse a recorder file back into one postmortem record, tolerating a
    truncated last line (the process may have died mid-write — that is the
    point). Returns None when the file is missing or empty.

    ``inflight_trace_ids`` is replayed from begin/end lines, so it is
    correct even for SIGKILL (no dump line); when a dump IS present its
    spans and metric deltas ride along."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return None
    process = None
    begins: Dict[str, float] = {}
    ended = set()
    total_begins = total_ends = 0
    dump: Optional[Dict[str, Any]] = None
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail line
        ev = rec.get("event")
        if ev == "open":
            process = rec.get("process", process)
        elif ev == "begin" and rec.get("trace_id"):
            begins[rec["trace_id"]] = rec.get("ts", 0.0)
            total_begins += 1
        elif ev == "end" and rec.get("trace_id"):
            ended.add(rec["trace_id"])
            total_ends += 1
        elif ev == "dump":
            dump = rec
            process = rec.get("process", process)
    if process is None and not begins and dump is None:
        return None
    inflight = sorted(t for t in begins if t not in ended)
    out: Dict[str, Any] = {
        "path": path,
        "process": process,
        "begins": total_begins,
        "ends": total_ends,
        "inflight_trace_ids": inflight,
        "dumped": dump is not None,
    }
    if dump is not None:
        out["reason"] = dump.get("reason")
        out["spans"] = dump.get("spans", [])
        out["metric_deltas"] = dump.get("metric_deltas", {})
    return out
