"""Minimal stdlib client for :class:`~sparkflow_tpu.serving.server.InferenceServer`.

Deliberately tiny — ``urllib.request`` plus JSON — because its jobs are the
smoke path (``make serve-smoke``), the e2e tests, and showing the wire
protocol in ~30 lines. Production callers can speak the same JSON from any
HTTP stack.

Resilience: :meth:`ServingClient.predict` retries connection errors and
``503`` rejections (queue-full backpressure, drains during a rolling
restart) with jittered exponential backoff, honoring the server's
``Retry-After`` hint and a hard wall-clock deadline. ``retries=0`` opts a
call out entirely (first error propagates untouched).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np

from ..resilience.retry import RetryExhausted, RetryPolicy


class ServingError(Exception):
    """Non-2xx reply from the server. Carries the structured error body and,
    when the server sent one, the ``Retry-After`` hint (seconds)."""

    def __init__(self, status: int, code: str, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"HTTP {status} [{code}]: {message}")
        self.status = status
        self.code = code
        self.message = message
        self.retry_after = retry_after


class ServingClient:
    """``ServingClient(url).predict(rows)`` → np.ndarray of predictions.

    ``retries`` is the default number of re-attempts after a retryable
    failure (connection refused/reset, HTTP 503); ``retry_policy`` (a
    :class:`~sparkflow_tpu.resilience.retry.RetryPolicy`) shapes the backoff
    and supplies the optional ``deadline_s`` — the default policy backs off
    0.1s/0.2s/0.4s... (jittered) with no deadline. A spent budget raises
    :class:`~sparkflow_tpu.resilience.retry.RetryExhausted` chained to the
    last error.
    """

    def __init__(self, url: str, timeout: float = 30.0, retries: int = 3,
                 retry_policy: Optional[RetryPolicy] = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.retries = int(retries)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=self.retries + 1, base_s=0.1, multiplier=2.0,
            max_s=5.0, jitter=0.5, seed=0)

    def _request(self, path: str, payload: Optional[Dict[str, Any]] = None,
                 headers: Optional[Dict[str, str]] = None,
                 with_headers: bool = False):
        req = urllib.request.Request(
            self.url + path,
            data=(json.dumps(payload).encode("utf-8")
                  if payload is not None else None),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST" if payload is not None else "GET")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = json.loads(resp.read().decode("utf-8"))
                if with_headers:
                    return body, dict(resp.headers)
                return body
        except urllib.error.HTTPError as exc:
            ra = exc.headers.get("Retry-After") if exc.headers else None
            try:
                retry_after = float(ra) if ra is not None else None
            except ValueError:
                retry_after = None
            try:
                err = json.loads(exc.read().decode("utf-8"))["error"]
                raise ServingError(exc.code, err.get("code", "unknown"),
                                   err.get("message", ""),
                                   retry_after) from None
            except (ValueError, KeyError):
                raise ServingError(exc.code, "unknown", str(exc),
                                   retry_after) from None

    @staticmethod
    def _retryable(exc: Exception) -> bool:
        if isinstance(exc, ServingError):
            return exc.status == 503  # queue_full / draining backpressure
        # URLError covers connection refused/reset and socket timeouts
        return isinstance(exc, urllib.error.URLError)

    def predict(self, inputs, retries: Optional[int] = None) -> np.ndarray:
        """``inputs``: rows (list/array) or, for multi-input engines, a dict
        of ``{input_name: rows}``. Retryable failures (connection errors,
        503) back off and re-send up to ``retries`` times (default: the
        client's setting; 0 = fail fast); anything else — 400s, 500s —
        raises :class:`ServingError` immediately."""
        if isinstance(inputs, dict):
            wire: Any = {k: np.asarray(v).tolist() for k, v in inputs.items()}
        else:
            wire = np.asarray(inputs).tolist()
        payload = {"inputs": wire}
        budget = (self.retries if retries is None else int(retries)) + 1
        policy = self.retry_policy
        start = policy.clock()
        attempt = 0
        while True:
            try:
                reply = self._request("/v1/predict", payload)
                return np.asarray(reply["predictions"])
            except (ServingError, urllib.error.URLError) as e:
                attempt += 1
                if not self._retryable(e) or attempt >= budget:
                    raise
                delay = policy.backoff(attempt - 1)
                hint = getattr(e, "retry_after", None)
                if hint is not None:
                    # the server knows its own drain/queue horizon better
                    # than our backoff curve does
                    delay = max(delay, float(hint))
                elapsed = policy.clock() - start
                if (policy.deadline_s is not None
                        and elapsed + delay > policy.deadline_s):
                    raise RetryExhausted(
                        f"predict against {self.url}", attempt, elapsed,
                        e) from e
                policy.sleep(delay)

    def predict_full(self, inputs,
                     request_id: Optional[str] = None) -> Dict[str, Any]:
        """One attempt (no retries), full reply: ``predictions``, ``rows``,
        the server's ``request_id`` (yours, echoed, if you passed one) and
        the per-request ``timing_ms`` latency decomposition. The echoed
        ``X-Request-Id`` response header is surfaced as
        ``x_request_id_header``."""
        if isinstance(inputs, dict):
            wire: Any = {k: np.asarray(v).tolist() for k, v in inputs.items()}
        else:
            wire = np.asarray(inputs).tolist()
        body, hdrs = self._request(
            "/v1/predict", {"inputs": wire},
            headers=({"X-Request-Id": request_id} if request_id else None),
            with_headers=True)
        body["x_request_id_header"] = hdrs.get("X-Request-Id")
        return body

    def healthz(self) -> Dict[str, Any]:
        return self._request("/healthz")

    def metrics(self) -> Dict[str, Any]:
        return self._request("/metrics")

    def metrics_prometheus(self) -> str:
        """Raw Prometheus text exposition from
        ``GET /metrics?format=prometheus``."""
        req = urllib.request.Request(self.url + "/metrics?format=prometheus")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8")
