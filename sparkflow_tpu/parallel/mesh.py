"""Mesh construction over local or distributed TPU devices."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    Axis sizes must multiply to the device count; ``-1`` for one axis means
    "use the remaining devices" (like a reshape wildcard).
    """
    devs = np.array(devices if devices is not None else jax.devices())
    sizes = list(axes.values())
    n_unknown = sum(1 for s in sizes if s == -1)
    if n_unknown > 1:
        raise ValueError("at most one axis size may be -1")
    if n_unknown == 1:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        if devs.size % known:
            raise ValueError(f"{devs.size} devices not divisible by {known}")
        sizes = [s if s != -1 else devs.size // known for s in sizes]
    if int(np.prod(sizes)) != devs.size:
        raise ValueError(f"mesh {dict(zip(axes, sizes))} needs "
                         f"{int(np.prod(sizes))} devices, have {devs.size}")
    return Mesh(devs.reshape(sizes), tuple(axes.keys()))


def default_mesh(axis: str = "dp") -> Optional[Mesh]:
    """All local devices on one data-parallel axis; None on a single device
    (plain jit is faster than a 1-device mesh)."""
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    return Mesh(np.array(devs), (axis,))


def mesh_axis_size(mesh: Optional[Mesh], axis: str) -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def parse_mesh_shape(s: str) -> Dict[str, int]:
    """Parse a mesh-shape string Param like ``"dp=2,tp=4"`` into axis sizes.

    Accepted axes: dp, tp, fsdp, sp, pp, ep. One axis may be ``-1``
    (remaining devices, like :func:`make_mesh`). This is the estimator-facing
    config format — a plain string so it persists like every reference Param.
    """
    known = ("dp", "tp", "fsdp", "sp", "pp", "ep")
    axes: Dict[str, int] = {}
    for part in (p.strip() for p in s.split(",") if p.strip()):
        if "=" not in part:
            raise ValueError(
                f"meshShape entry {part!r} is not 'axis=size' (got {s!r})")
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in known:
            raise ValueError(
                f"unknown mesh axis {name!r} in meshShape {s!r}; "
                f"known axes: {', '.join(known)}")
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r} in {s!r}")
        try:
            axes[name] = int(size)
        except ValueError:
            raise ValueError(
                f"mesh axis size {size!r} for {name!r} is not an integer")
    if not axes:
        raise ValueError(f"empty meshShape {s!r}")
    return axes
