"""Utilities: data plane binding, profiling/tracing, structured metrics."""

from . import data, metrics, tracing

__all__ = ["data", "metrics", "tracing"]
