"""graftcheck: static + trace analysis for sharding, tracing, and
concurrency correctness (docs/analysis.md).

Three analyzers behind one CLI (``python -m sparkflow_tpu.analysis``) and
this library API:

- :mod:`~sparkflow_tpu.analysis.jaxpr_lint` — abstract-traces a model or
  train step (``jax.make_jaxpr``/``eval_shape``) against a mesh +
  PartitionSpecs: implicit reshards, large replicated tensors, f64/weak-
  type promotion, missed donation. Nothing executes or compiles.
- :mod:`~sparkflow_tpu.analysis.ast_lint` +
  :mod:`~sparkflow_tpu.analysis.locks` — source rules, no imports of the
  scanned code: host syncs and Python branching inside jit'd functions,
  PRNG key reuse, unhashable static args, and shared-state mutation
  outside the owning class's lock.
- :mod:`~sparkflow_tpu.analysis.lockgraph` — the whole-package
  concurrency pass: one cross-module lock-acquisition graph, reporting
  lock-order cycles (GC-L304) and blocking calls under a held lock
  (GC-L305).
- :mod:`~sparkflow_tpu.analysis.runtime_guards` —
  :class:`RecompileGuard` / :func:`track_recompiles`: count jit retraces
  live and name which argument's shape/dtype/static value changed.
- :mod:`~sparkflow_tpu.analysis.racecheck` — an Eraser-style dynamic
  lockset race detector (GC-R402) for tests/chaos runs:
  :class:`RaceTracker` + drop-in lock/attribute instrumentation, enabled
  by ``SPARKFLOW_TPU_RACECHECK=1`` and free when off.
- :mod:`~sparkflow_tpu.analysis.lifecycle` +
  :mod:`~sparkflow_tpu.analysis.restrack` — resource lifecycles, both
  directions: a static acquire/release pairing lint over a declarative
  pair registry (leaks on escape/error, unreaped threads, gauge
  namespaces with no cleanup — GC-X601..X604) and its runtime twin, a
  per-resource balance tracker with acquisition stacks (GC-X605),
  enabled by ``SPARKFLOW_TPU_RESTRACK=1`` and free when off.

The repo keeps itself clean under the full pass: ``make lint-graft`` (and
``tests/test_analysis.py``) runs it over ``sparkflow_tpu/`` and
``examples/`` and asserts zero findings.
"""

from __future__ import annotations

from .findings import Finding, RULES, format_findings
from .runtime_guards import (RecompileGuard, describe_signature_diff,
                             trace_probe, track_recompiles)

__all__ = [
    "Finding", "RULES", "format_findings",
    "RecompileGuard", "track_recompiles", "trace_probe",
    "describe_signature_diff",
    "run_static", "run_all",
    "lint_fn", "lint_train_step", "lint_apply",
    "ast_lint", "locks", "lockgraph", "jaxpr_lint", "racecheck",
    "runtime_guards", "lifecycle", "restrack",
]


def __getattr__(name):
    # lazy: jaxpr_lint pulls in models/optimizers; the static passes and
    # the CLI must stay usable without importing any of that until needed
    import importlib
    if name in ("lint_fn", "lint_train_step", "lint_apply"):
        return getattr(importlib.import_module(".jaxpr_lint", __name__),
                       name)
    if name in ("run_static", "run_all"):
        return getattr(importlib.import_module(".cli", __name__), name)
    if name in ("ast_lint", "locks", "lockgraph", "jaxpr_lint", "racecheck",
                "runtime_guards", "lifecycle", "restrack"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
