"""Feature transformers: the ``pyspark.ml.feature`` subset the reference examples
use (``VectorAssembler``, ``OneHotEncoder``, ``Normalizer`` — see reference
``examples/simple_dnn.py:40-41``, ``examples/autoencoder_example.py:26-27``)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .base import Transformer
from .linalg import DenseVector, SparseVector, Vectors, vector_to_array
from .param import Param, Params, TypeConverters, keyword_only, HasInputCol, HasOutputCol
from .sql import DataFrame, Row


class VectorAssembler(Transformer, HasInputCol, HasOutputCol):
    """Concatenates numeric / vector columns into one DenseVector column."""

    inputCols = Param(Params._dummy(), "inputCols", "input column names",
                      typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCols=None, outputCol=None):
        super().__init__()
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getInputCols(self) -> List[str]:
        return self.getOrDefault(self.inputCols)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_cols = self.getInputCols()
        out_col = self.getOrDefault(self.outputCol)
        rows = []
        for r in dataset.collect():
            parts = [vector_to_array(r[c]) for c in in_cols]
            vec = Vectors.dense(np.concatenate(parts))
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class OneHotEncoder(Transformer, HasInputCol, HasOutputCol):
    """Category index -> one-hot sparse vector (pyspark 2.x OneHotEncoder
    semantics: transform-only; vector size inferred as max(index)+1; dropLast
    drops the final category — the reference uses ``dropLast=False``,
    ``examples/simple_dnn.py:41``)."""

    dropLast = Param(Params._dummy(), "dropLast", "drop the last category",
                     typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, dropLast=True):
        super().__init__()
        self._setDefault(dropLast=True)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getDropLast(self) -> bool:
        return self.getOrDefault(self.dropLast)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        drop_last = self.getDropLast()
        values = [int(r[in_col]) for r in dataset.collect()]
        size = (max(values) + 1) if values else 0
        if drop_last:
            size -= 1
        rows = []
        for r, v in zip(dataset.collect(), values):
            if v < size:
                vec = SparseVector(size, [v], [1.0])
            else:  # dropped last category encodes as all-zeros
                vec = SparseVector(size, [], [])
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class Normalizer(Transformer, HasInputCol, HasOutputCol):
    """Scale each vector to unit p-norm (reference autoencoder example uses
    p=1.0, ``examples/autoencoder_example.py:27``)."""

    p = Param(Params._dummy(), "p", "norm order", typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, p=2.0):
        super().__init__()
        self._setDefault(p=2.0)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getP(self) -> float:
        return self.getOrDefault(self.p)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        p = self.getP()
        rows = []
        for r in dataset.collect():
            arr = vector_to_array(r[in_col])
            norm = np.linalg.norm(arr, ord=p)
            vec = Vectors.dense(arr / norm if norm > 0 else arr)
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class WordpieceEncoder(Transformer, HasInputCol, HasOutputCol):
    """Text column -> fixed-shape token-id vector + attention-mask columns,
    ready for ``SparkAsyncDL`` transformer models
    (``extraInputCols=maskCol``). Backed by the native C++ WordPiece
    tokenizer (``sparkflow_tpu/native/tokenizer.cpp``); python fallback
    otherwise. No pyspark analog exists — a capability upgrade over the
    reference, which has no text front-end at all (SURVEY.md §5)."""

    maskCol = Param(Params._dummy(), "maskCol", "attention mask column",
                    typeConverter=TypeConverters.toString)
    maxLen = Param(Params._dummy(), "maxLen", "sequence length",
                   typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, maskCol=None,
                 maxLen=None, vocab=None):
        super().__init__()
        self._setDefault(maskCol="mask", maxLen=128)
        self._vocab = list(vocab) if vocab is not None else None
        kwargs = dict(self._input_kwargs)
        kwargs.pop("vocab", None)
        self._set(**{k: v for k, v in kwargs.items() if v is not None})

    def setVocab(self, vocab) -> "WordpieceEncoder":
        self._vocab = list(vocab)
        return self

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from ..utils.text import WordpieceTokenizer, build_vocab
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        mask_col = self.getOrDefault(self.maskCol)
        max_len = self.getOrDefault(self.maxLen)
        rows = dataset.collect()
        texts = [str(r[in_col]) for r in rows]
        vocab = self._vocab
        if vocab is None:  # fit-free convenience: derive from this dataset
            vocab = build_vocab(texts)
            self._vocab = vocab
        tok = WordpieceTokenizer(vocab)
        ids, mask = tok.encode_batch(texts, max_len)
        out = []
        for r, i, m_ in zip(rows, ids, mask):
            out.append(Row(**{**r.asDict(),
                              out_col: Vectors.dense(i.astype(float)),
                              mask_col: Vectors.dense(m_.astype(float))}))
        cols = dataset.columns + [c for c in (out_col, mask_col)
                                  if c not in dataset.columns]
        return DataFrame(out, cols, dataset.num_partitions)
