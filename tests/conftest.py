"""Test harness: JAX on CPU with 8 virtual devices.

Mirrors the reference's test trick of simulating a cluster locally (a real Flask
parameter server + `local[2]` Spark, reference ``tests/dl_runner.py:26-40``): here
the *real* collective/sharding paths run on a virtual 8-device CPU mesh, so
multi-chip code is exercised without TPU hardware.

NOTE: the axon TPU plugin's sitecustomize overrides ``JAX_PLATFORMS`` env; forcing
the platform must happen via jax.config before any device use.
"""

import os

# Escape hatch: SPARKFLOW_TEST_PLATFORM=native leaves the real backend (axon
# TPU) in place so the @skipif(backend != 'tpu') hardware tests can actually
# run — without it the cpu forcing below makes them permanently dead code.
_NATIVE = os.environ.get("SPARKFLOW_TEST_PLATFORM", "cpu") == "native"

if not _NATIVE:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if not _NATIVE:
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass  # already initialized with the right settings (e.g. driver-run)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def dp_mesh():
    from jax.sharding import Mesh
    devs = np.array(jax.devices())
    return Mesh(devs.reshape(devs.size), ("dp",))


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(12345)


@pytest.fixture(scope="session")
def sharded_attn_mesh():
    """2x4 {dp, tp} mesh for the sharded-jit attention tests."""
    import numpy as np
    from jax.sharding import Mesh

    import jax
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
