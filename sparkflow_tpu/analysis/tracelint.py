"""GC-T701 untraced-dispatch: trace-propagation lint for dispatch sites.

Distributed traces only assemble if every cross-process hop forwards the
``traceparent`` context. A hop that drops it does not fail loudly — the
request still works, the replica still answers — but its spans mint a
fresh trace id and silently fall off the request's timeline, which is
exactly the blind spot tracing exists to close. This analyzer makes the
propagation contract mechanical instead of reviewed-by-eyeball.

A dispatch site opts in with a marker comment, either trailing on the
call line or on its own line immediately above the call::

    # graftcheck: dispatch-site
    status, hdrs, data = self._call_replica(replica, body, headers)

Every registered site is then required to show evidence of propagation,
in either of two places:

- the **enclosing function** references the traceparent header — any
  identifier (name, attribute, argument) containing ``traceparent``, or
  the ``"traceparent"`` string literal itself; or
- the **call itself** carries trace context — an argument or keyword
  whose name mentions ``trace`` (``traceparent=ctx``, ``trace_id=tid``,
  a ``trace_headers`` variable, ...).

A marker with no call on its own or the following line is also flagged:
stale markers rot into false confidence that a site is covered.

Suppression follows the standard graftcheck syntax (trailing
``# graftcheck: disable=GC-T701`` / file-level ``disable-file=``), and
the rule runs in the full static pass (``make lint-graft-strict``), which
the repo itself must keep clean.
"""

from __future__ import annotations

import ast
import io
import tokenize
from typing import Iterable, List, Optional, Tuple

from .ast_lint import iter_py_files
from .findings import Finding, filter_suppressed

__all__ = ["DISPATCH_MARKER", "lint_source", "lint_file", "lint_paths"]

DISPATCH_MARKER = "graftcheck: dispatch-site"

#: evidence tokens, compared case-insensitively against identifiers
_HEADER_TOKEN = "traceparent"
_ARG_TOKEN = "trace"


def _identifiers(node: ast.AST) -> Iterable[str]:
    """Every identifier-ish string in a subtree: names, attributes,
    function arguments, keyword names, and string constants."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr
        elif isinstance(sub, ast.arg):
            yield sub.arg
        elif isinstance(sub, ast.keyword) and sub.arg is not None:
            yield sub.arg
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _mentions(node: ast.AST, token: str) -> bool:
    return any(token in ident.lower() for ident in _identifiers(node))


def _call_carries_trace(call: ast.Call) -> bool:
    for part in list(call.args) + list(call.keywords):
        if _mentions(part, _ARG_TOKEN):
            return True
    return False


class _CallIndex(ast.NodeVisitor):
    """Every Call node paired with its innermost enclosing function (or
    the module node for top-level calls)."""

    def __init__(self, tree: ast.Module):
        self.calls: List[Tuple[ast.Call, ast.AST]] = []
        self._scope: List[ast.AST] = [tree]
        self.visit(tree)

    def _enter(self, node: ast.AST) -> None:
        self._scope.append(node)
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.calls.append((node, self._scope[-1]))
        self.generic_visit(node)


def lint_source(source: str, path: Optional[str] = None) -> List[Finding]:
    """Lint one module's source; returns [] unless it registers dispatch
    sites with the marker."""
    # tokenize, not a line scan: the marker only registers in real
    # comments, never in docstrings or string literals that merely talk
    # about it (this module's own docs would otherwise self-flag)
    marked: List[int] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if (tok.type == tokenize.COMMENT
                    and DISPATCH_MARKER in tok.string):
                marked.append(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []   # the interpreter's problem, not this lint's
    if not marked:
        return []
    try:
        tree = ast.parse(source, filename=path or "<tracelint>")
    except SyntaxError:
        return []   # the interpreter's problem, not this lint's
    index = _CallIndex(tree)
    findings: List[Finding] = []
    for mline in marked:
        # trailing marker: call on the marker line; own-line marker: call
        # on the next line. Outermost call wins (smallest column).
        site = None
        for target in (mline, mline + 1):
            on_line = [(c, scope) for c, scope in index.calls
                       if c.lineno == target]
            if on_line:
                site = min(on_line, key=lambda cs: cs[0].col_offset)
                break
        if site is None:
            findings.append(Finding(
                "GC-T701", "dispatch-site marker with no call on this or "
                "the following line — the marker has rotted away from the "
                "code it was meant to register", path=path, line=mline,
                source="tracelint"))
            continue
        call, scope = site
        if _mentions(scope, _HEADER_TOKEN) or _call_carries_trace(call):
            continue
        findings.append(Finding(
            "GC-T701", "registered dispatch site sends a request without "
            "propagating trace context — the enclosing function never "
            "touches the traceparent header and no call argument carries "
            "trace context, so downstream spans mint a fresh trace and "
            "fall off this request's timeline", path=path,
            line=call.lineno, source="tracelint"))
    findings.sort(key=lambda f: (f.line or 0, f.message))
    return filter_suppressed(findings, source)


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f))
    return findings
