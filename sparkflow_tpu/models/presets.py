"""Graph-DSL preset builders for the reference's three example architectures.

These return ``build_graph`` JSON, so they flow through the Estimator exactly
like hand-written model functions (reference ``examples/*.py``)."""

from __future__ import annotations

from typing import Sequence

from .. import nn
from ..graph_utils import build_graph
from .registry import build_registry_spec


def mlp(input_dim: int, num_classes: int, hidden: Sequence[int] = (256, 256),
        activation: str = "relu") -> str:
    """The simple_dnn.py MLP shape (reference examples/simple_dnn.py:13-22)."""

    def model():
        x = nn.placeholder([None, input_dim], name="x")
        y = nn.placeholder([None, num_classes], name="y")
        h = x
        for units in hidden:
            h = nn.dense(h, units, activation=activation)
        out = nn.dense(h, num_classes, name="out")
        nn.argmax(out, 1, name="pred")
        nn.softmax_cross_entropy(y, out)

    return build_graph(model)


def cnn(side: int = 28, channels: int = 1, num_classes: int = 10) -> str:
    """The cnn_example.py conv net (reference examples/cnn_example.py:10-22)."""

    def model():
        x = nn.placeholder([None, side * side * channels], name="x")
        y = nn.placeholder([None, num_classes], name="y")
        xr = nn.reshape(x, [-1, side, side, channels])
        c1 = nn.conv2d(xr, 32, 5, activation="relu")
        p1 = nn.max_pooling2d(c1, 2, 2)
        c2 = nn.conv2d(p1, 64, 3, activation="relu")
        p2 = nn.max_pooling2d(c2, 2, 2)
        out = nn.dense(nn.flatten(p2), num_classes, name="out")
        nn.argmax(out, 1, name="pred")
        nn.softmax_cross_entropy(y, out)

    return build_graph(model)


def autoencoder(input_dim: int = 784,
                widths: Sequence[int] = (256, 128, 256)) -> str:
    """The autoencoder_example.py stack; bottleneck exposed as 'out/Sigmoid:0'
    (reference examples/autoencoder_example.py:9-16)."""
    mid = len(widths) // 2

    def model():
        x = nn.placeholder([None, input_dim], name="x")
        h = x
        for i, w in enumerate(widths):
            name = "out" if i == mid else None
            act = "sigmoid" if i == mid else "relu"
            h = nn.dense(h, w, activation=act, name=name)
        recon = nn.dense(h, input_dim, activation="sigmoid")
        nn.mean_squared_error(recon, x)

    return build_graph(model)


def moe_lm(vocab_size: int, *, hidden: int = 256, num_layers: int = 4,
           num_heads: int = 8, mlp_dim: int = 1024, max_len: int = 512,
           num_experts: int = 8, router_top_k: int = 2, moe_every: int = 2,
           capacity_factor: float = 1.25, dropout: float = 0.0) -> str:
    """Registry spec for a mixture-of-experts decoder LM sized for serving.

    The defaults keep ``num_experts`` divisible across an ``('ep',)`` mesh
    (expert-parallel decode, docs/serving.md) and ``num_heads`` divisible
    across a ``('tp',)`` mesh, so the same spec serves replicated, tensor-
    parallel, or expert-parallel without edits. Returns registry JSON for
    ``model_from_json`` — NOT graph-DSL JSON like the builders above."""
    return build_registry_spec(
        "transformer_moe_lm", vocab_size=vocab_size, hidden=hidden,
        num_layers=num_layers, num_heads=num_heads, mlp_dim=mlp_dim,
        max_len=max_len, num_experts=num_experts, router_top_k=router_top_k,
        moe_every=moe_every, capacity_factor=capacity_factor, dropout=dropout)
