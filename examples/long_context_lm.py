"""Long-context causal LM with ring-attention sequence parallelism.

Demonstrates the framework's long-context path: the sequence axis shards over
an ``sp`` mesh ring, K/V blocks rotate over ICI, and per-device memory is
O(S / n_devices) — contexts far beyond one chip's HBM train without code
changes. Runs on the virtual CPU mesh for demonstration; the same code spans a
real pod slice.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np


def main():
    import jax

    if jax.device_count() < 4:  # demo needs a mesh; force the virtual one
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass

    import jax.numpy as jnp
    from sparkflow_tpu.models import build_registry_spec, model_from_json
    from sparkflow_tpu.optimizers import build_optimizer
    from sparkflow_tpu.parallel.mesh import make_mesh
    from sparkflow_tpu.parallel.sp import make_sp_train_step

    smoke = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))
    sp = 4
    dp = max(1, jax.device_count() // sp)
    seq = 512 if smoke else 8192          # global context length
    spec = build_registry_spec(
        "transformer_lm", vocab_size=512,
        hidden=64 if smoke else 512,
        num_layers=2 if smoke else 8,
        num_heads=4 if smoke else 8,
        mlp_dim=128 if smoke else 2048,
        # 'dots' saves matmul outputs and recomputes only the cheap
        # elementwise ops — far less backward recompute than full remat,
        # still bounded activation memory at long sequence lengths
        max_len=seq, dropout=0.0, remat="dots" if not smoke else False)

    lm = model_from_json(spec)
    mesh = make_mesh({"dp": dp, "sp": sp})
    print(f"mesh: dp={dp} x sp={sp}, context length {seq}")

    optimizer = build_optimizer("adam", 3e-4, None)
    params = lm.init(jax.random.PRNGKey(0))
    opt_state = optimizer.init(params)
    step = make_sp_train_step(lm, optimizer, mesh)

    rs = np.random.RandomState(0)
    batch = 2 * dp
    for i in range(3):
        ids = jnp.asarray(rs.randint(0, 512, (batch, seq)), jnp.int32)
        mask = jnp.ones((batch, seq), jnp.float32)
        params, opt_state, loss = step(params, opt_state, ids, mask,
                                       jax.random.PRNGKey(i))
        print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    main()
