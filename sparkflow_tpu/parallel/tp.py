"""Tensor-parallel / FSDP sharded training via GSPMD.

Models expose ``param_pspecs()`` (megatron rules for transformers); placing
params with those shardings and jitting the standard step lets XLA partition
every matmul over ``tp`` and insert the all-reduces on ICI. ``fsdp_pspecs``
derives ZeRO-style parameter sharding for any model (shard the largest axis of
every big tensor over ``fsdp``); optimizer state inherits placement from params
because ``optax.init`` is a pure tree op.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import _step_body, make_loss_fn
from ..sharding import at_rest_leaf_spec


def filter_pspec(spec: P, mesh: Mesh) -> P:
    """Drop axes the mesh doesn't have (so e.g. megatron 'tp' rules place
    cleanly on an {'ep'}-only or {'dp'}-only mesh as replicated)."""
    def keep(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in mesh.axis_names)
            return kept if kept else None
        return a if a in mesh.axis_names else None

    return P(*(keep(a) for a in spec))


def shard_params(params, mesh: Mesh, pspecs):
    """Place a params pytree onto the mesh per a PartitionSpec pytree; spec
    axes absent from the mesh degrade to replication."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, filter_pspec(s, mesh))),
        params, pspecs,
        is_leaf=lambda x: not isinstance(x, dict))


def fsdp_pspecs(param_specs, axis: str = "fsdp", min_size: int = 2 ** 16):
    """ZeRO-style specs from a model's ``param_specs()``: big tensors shard
    their largest dim over ``axis``; small ones replicate. The per-leaf rule
    is :func:`~sparkflow_tpu.sharding.at_rest_leaf_spec` (``layout='gspmd'``)
    — the SAME decision the flat ZeRO-3 layout applies to its ``[n, s]``
    leaves, expressed on tensors kept in model shape."""
    return {
        lname: {
            pname: at_rest_leaf_spec(shape, axis, layout="gspmd",
                                     min_size=min_size)
            for pname, (shape, _init) in pspec.items()}
        for lname, pspec in param_specs.items()}


def make_sharded_train_step(model, optimizer, mesh: Mesh, input_name: str,
                            label_name: Optional[str], dp_axis: str = "dp"):
    """Jitted train step where params carry their own (tp/fsdp) shardings and
    the batch shards over ``dp_axis``. Use together with :func:`shard_params`:

        params = shard_params(model.init(rng), mesh, model.param_pspecs())
        opt_state = optimizer.init(params)           # inherits placement
        step = make_sharded_train_step(model, optimizer, mesh, 'input_ids', 'y')
        params, opt_state, loss = step(params, opt_state, x, y, mask, rng)
    """
    loss_fn = make_loss_fn(model, input_name, label_name)
    from ..core import _sharded_trace_guard
    step = _sharded_trace_guard(_step_body(loss_fn, optimizer), mesh,
                                batch_axis=dp_axis)
    data = NamedSharding(mesh, P(dp_axis))
    repl = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(None, None, data, data, data, repl),
                   donate_argnums=(0, 1))


def tp_pack_params(model, params, tp: int):
    """Host-side relayout of a transformer params tree for **shard_map**
    tensor parallelism (the decode plane's form — GSPMD jit needs none of
    this, sharding there is metadata only).

    Under shard_map each rank sees a contiguous column block of
    ``qkv_kernel``, but the kernel packs its output as ``(3, heads, d)``
    flattened — a naive block mixes q/k/v rows of unrelated heads. Permuting
    columns to ``(tp, 3, heads/tp, d)`` order makes rank r's block exactly
    ``[q_r | k_r | v_r]``, so the block-local ``reshape(b, 3, H/tp, d)``
    recovers its own heads (``qkv_bias`` permutes identically). The
    row-parallel biases (``o_bias``, ``fc2_bias``) divide by ``tp`` so the
    decode-step psum restores them exactly once — exact in floating point
    for power-of-two ``tp``. Expert banks / router / norms / embeddings pass
    through untouched (experts shard whole-expert over ``ep``; everything
    else is replicated or column-natural)."""
    if tp <= 1:
        return params
    import jax.numpy as jnp
    H, d = int(model.num_heads), int(model.head_dim)
    if H % tp:
        raise ValueError(f"num_heads={H} is not divisible by tp={tp}")
    perm = jnp.transpose(jnp.arange(3 * H * d).reshape(3, tp, H // tp, d),
                         (1, 0, 2, 3)).reshape(-1)

    def pack_block(bp):
        if any(k.endswith("kernel_q8") for k in bp):
            raise ValueError(
                "tensor-parallel serving does not compose with int8-"
                "quantized params; quantize or shard the model, not both")
        bp = dict(bp)
        bp["qkv_kernel"] = jnp.asarray(bp["qkv_kernel"])[:, perm]
        if "qkv_bias" in bp:
            bp["qkv_bias"] = jnp.asarray(bp["qkv_bias"])[perm]
        if "o_bias" in bp:
            bp["o_bias"] = jnp.asarray(bp["o_bias"]) / tp
        if "fc2_bias" in bp:
            bp["fc2_bias"] = jnp.asarray(bp["fc2_bias"]) / tp
        return bp

    return {name: (pack_block(sub) if isinstance(sub, dict)
                   and "qkv_kernel" in sub else sub)
            for name, sub in params.items()}


def rename_pspec_axes(pspecs, mapping: dict):
    """Rename axis names inside a PartitionSpec pytree — e.g. the megatron
    rules' literal ``'tp'``/``'ep'`` onto a ShardingConfig's configured
    ``tp_axis``/``ep_axis``. Axes not in ``mapping`` pass through."""
    def rename_entry(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            return tuple(mapping.get(x, x) for x in a)
        return mapping.get(a, a)

    return jax.tree.map(
        lambda s: P(*(rename_entry(a) for a in s)),
        pspecs, is_leaf=lambda x: isinstance(x, P))


def derive_param_pspecs(model, mesh: Mesh, sharding=None):
    """Parameter PartitionSpecs for running ``model`` on ``mesh``.

    - mesh has ``tp``/``ep`` (or the axes a ``sharding`` config names for
      them) and the model publishes megatron-style rules (``param_pspecs``,
      transformer/resnet/moe families) -> those rules, renamed to the
      configured axes (axes absent from the mesh degrade to replication via
      :func:`filter_pspec` inside :func:`shard_params`);
    - mesh has ``fsdp`` -> ZeRO-style :func:`fsdp_pspecs` derived from the
      model's ``param_specs()`` — works for ANY model incl. the ``nn``-DSL
      graphs (largest dim of every big tensor shards, small ones replicate);
    - otherwise (pure dp) -> ``None``: replicate params, shard the batch.

    Both branches derive from ONE per-leaf decision
    (:func:`~sparkflow_tpu.sharding.at_rest_leaf_spec` for the at-rest
    layouts; the model's own megatron table for compute sharding) — this is
    the single spec-derivation entry point the trainer AND the serving
    engines call.
    """
    names = {"tp": "tp", "ep": "ep"}
    if sharding is not None:
        if getattr(sharding, "tp_axis", None):
            names["tp"] = sharding.tp_axis
        if getattr(sharding, "ep_axis", None):
            names["ep"] = sharding.ep_axis
    has_tp = any(a in mesh.axis_names for a in (names["tp"], names["ep"]))
    has_fsdp = "fsdp" in mesh.axis_names
    if has_tp and has_fsdp:
        # auto-composing megatron rules WITH ZeRO sharding needs per-tensor
        # axis assignments no heuristic can guess; refusing beats silently
        # replicating one of the two requested shardings
        raise ValueError(
            "combined tp/ep + fsdp sharding cannot be auto-derived; pass an "
            "explicit PartitionSpec pytree (Trainer(param_sharding=...)) or "
            "drop one of the axes")
    if has_tp and hasattr(model, "param_pspecs"):
        specs = model.param_pspecs()
        if names["tp"] != "tp" or names["ep"] != "ep":
            specs = rename_pspec_axes(specs, {"tp": names["tp"],
                                              "ep": names["ep"]})
        return specs
    if has_fsdp and hasattr(model, "param_specs"):
        return fsdp_pspecs(model.param_specs(), axis="fsdp")
    return None
