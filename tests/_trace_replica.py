"""Subprocess replica for the flight-recorder harvest tests.

Run as ``python tests/_trace_replica.py --port N --flight-dir D
[--predict-delay-s S]``: a real :class:`InferenceServer` over a trivial
echo engine, flight recorder armed, SIGTERM drain handlers installed.
``--predict-delay-s`` makes every predict sleep, so the parent test can
SIGKILL the process with a request (and its flight ``begin`` line)
provably in flight.
"""

import argparse
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--flight-dir", required=True)
    parser.add_argument("--predict-delay-s", type=float, default=0.0)
    ns = parser.parse_args()

    import numpy as np

    from sparkflow_tpu.resilience.lifecycle import ServerState
    from sparkflow_tpu.serving import InferenceServer

    class EchoEngine:
        max_batch = 8

        def __init__(self, delay_s: float):
            self.delay_s = delay_s

        def predict(self, x):
            if self.delay_s:
                time.sleep(self.delay_s)
            return np.asarray(x)

        def stats(self):
            return {}

    server = InferenceServer(EchoEngine(ns.predict_delay_s), port=ns.port,
                             max_delay_ms=0.5, memory_watch=False,
                             flight_dir=ns.flight_dir)
    server.start()
    server.install_signal_handlers()
    print(f"replica up on {server.url}", flush=True)
    while server.lifecycle.state in (ServerState.STARTING,
                                     ServerState.SERVING):
        time.sleep(0.1)
    server.stop()


if __name__ == "__main__":
    main()
